"""ISSUE 10 tentpole part 3 — the BENCH trajectory regression sentinel.

Both-ways pins (the check_fleet/check_chaos discipline): the sentinel
passes the REAL r01–r05 trajectory checked into the repo (the r04→r05
4096² dip is single-sample/no-spread — UNKNOWN, never a page), and
exit-2s on a doctored steady-state regression whose own low spread
cannot explain it.  First-call compile-inclusive times are never
compared; rows without robust-capture stats are unknown, not
regressed (backfill tolerance); high-variance sessions — on either
end of the comparison — explain their own dips.  No jax import in the
checker itself.
"""

import importlib.util
import json
import pathlib

_repo = pathlib.Path(__file__).resolve().parent.parent
_tool = _repo / "tools" / "check_bench.py"
_spec = importlib.util.spec_from_file_location("check_bench", _tool)
check_bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_bench)


def _round(value, extra=None, metric="invert_4096x4096_f32_gflops"):
    return {"metric": metric, "value": value, "unit": "GFLOP/s",
            "extra": extra or {}}


def _write(tmp_path, name, row):
    p = tmp_path / name
    p.write_text(json.dumps({"rc": 0, "tail": "", "parsed": row}))
    return str(p)


class TestRealTrajectory:
    def test_real_r01_r05_passes(self):
        """The acceptance pin: the checked-in trajectory — including
        the diagnosed r04→r05 dip — exits 0."""
        files = sorted(str(p) for p in _repo.glob("BENCH_r0*.json"))
        assert len(files) >= 5
        assert check_bench.main(files) == 0

    def test_real_rounds_load(self):
        row = check_bench.load_round(str(_repo / "BENCH_r05.json"))
        assert row["metric"] == "invert_4096x4096_f32_gflops"
        keys = check_bench.comparable_keys(row)
        assert "invert_4096x4096_f32_gflops" in keys
        assert not any("first_call" in k for k in keys)


class TestRegressionRules:
    def test_doctored_quiet_regression_exits_2(self, tmp_path):
        """The exit-2 class: a 30% steady-state shortfall with 2%
        recorded spread — the session's own variance cannot explain
        it."""
        files = [
            _write(tmp_path, "BENCH_r01.json", _round(10000.0)),
            _write(tmp_path, "BENCH_r02.json", _round(
                7000.0, {"invert_4096_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 2

    def test_missing_spread_is_unknown_not_regressed(self, tmp_path):
        """Backfill tolerance (the r04→r05 class): a shortfall on a
        row WITHOUT robust-capture stats cannot be attributed — warn,
        never page."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0)),
            _write(tmp_path, "r2.json", _round(7000.0)),
        ]
        assert check_bench.main(files) == 0

    def test_high_variance_session_explains_its_dip(self, tmp_path):
        files = [
            _write(tmp_path, "r1.json", _round(10000.0)),
            _write(tmp_path, "r2.json", _round(
                7000.0, {"invert_4096_spread_pct": 31.0})),
        ]
        assert check_bench.main(files) == 0

    def test_variance_flag_explains_its_dip(self, tmp_path):
        files = [
            _write(tmp_path, "r1.json", _round(10000.0)),
            _write(tmp_path, "r2.json", _round(
                7000.0, {"invert_4096_spread_pct": 3.0,
                         "invert_4096_variance_flag":
                             "spread 3% but bimodal"})),
        ]
        assert check_bench.main(files) == 0

    def test_noisy_high_water_mark_explains_the_dip(self, tmp_path):
        """The reference round itself was noisy: its inflated best is
        not a page-worthy baseline."""
        files = [
            _write(tmp_path, "r1.json", _round(
                10000.0, {"invert_4096_spread_pct": 40.0})),
            _write(tmp_path, "r2.json", _round(
                7000.0, {"invert_4096_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 0

    def test_small_shortfall_within_tolerance(self, tmp_path):
        files = [
            _write(tmp_path, "r1.json", _round(10000.0)),
            _write(tmp_path, "r2.json", _round(
                9200.0, {"invert_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 0

    def test_first_call_keys_never_compared(self, tmp_path):
        """A 100x first-call regression (a compile-time change) with
        flat steady-state rows is NOT a regression — the exact
        conflation the PR 4 row split exists to prevent."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "invert_4096_first_call_compile_inclusive_s": 1.0,
                "invert_4096_spread_pct": 1.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "invert_4096_first_call_compile_inclusive_s": 100.0,
                "invert_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 0

    def test_extra_gflops_rows_compared_by_key(self, tmp_path):
        """Rows compare like-for-like by key: a regressed extra row
        pages even when the headline is healthy — and an exact-stem
        spread sibling is found first."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "invert_8192x8192_f32_m256_gflops": 14000.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "invert_8192x8192_f32_m256_gflops": 9000.0,
                "invert_8192x8192_f32_m256_spread_pct": 1.5})),
        ]
        assert check_bench.main(files) == 2

    def test_grouped_row_never_binds_the_plain_siblings_spread(
            self, tmp_path):
        """Fuzzy variance lookup is configuration-aware (review
        finding): the grouped2 row's quiet 1% spread — not the plain
        |i-j| row's noisy 25% — judges the grouped regression, so it
        pages."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "invert_8192_f32_m128_grouped2_rand_gflops": 16000.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "invert_8192_f32_m128_grouped2_rand_gflops": 12000.0,
                "invert_8192_spread_pct": 25.0,
                "invert_8192_grouped_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 2
        row = {"extra": {"invert_8192_spread_pct": 25.0,
                         "invert_8192_grouped_spread_pct": 1.0}}
        spread, _ = check_bench._variance_context(
            "invert_8192_f32_m128_grouped2_rand_gflops", row)
        assert spread == 1.0

    def test_suffix_style_spread_keys_recognized(self, tmp_path):
        """The 16384 scale row's historical suffix naming
        (spread_pct_16384) is visible to the sentinel (review
        finding): a quiet suffix spread pages a real regression, a
        noisy one explains it."""
        key = "invert_16384_f32_m128_grouped2_rand_gflops"
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {key: 22000.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                key: 15000.0, "spread_pct_16384": 1.2})),
        ]
        assert check_bench.main(files) == 2
        files[1] = _write(tmp_path, "r2b.json", _round(10000.0, {
            key: 15000.0, "spread_pct_16384": 30.0}))
        assert check_bench.main(files) == 0

    def test_xla_gflops_accounting_rows_never_compared(self, tmp_path):
        """A compiler upgrade that recounts flops for the SAME
        execution (fusion changes) must not page: the *_xla_gflops
        accounting rows are excluded from comparison, like first-call
        times (review finding)."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "invert_4096_xla_gflops": 13000.0,
                "invert_4096_spread_pct": 1.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "invert_4096_xla_gflops": 9000.0,
                "invert_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 0
        keys = check_bench.comparable_keys(
            {"metric": "m", "value": 1.0,
             "extra": {"invert_4096_xla_gflops": 9000.0,
                       "invert_4096_f32_gflops": 9000.0}})
        assert "invert_4096_f32_gflops" in keys
        assert "invert_4096_xla_gflops" not in keys

    def test_capacity_bytes_rows_accounting_class_never_compared(
            self, tmp_path):
        """ISSUE 13 satellite, trapped both ways: the new capacity
        accounting fields (``*_peak_hbm_bytes`` from memory_analysis,
        ``*_resident_handle_bytes``) are accounting-class — a 10x
        'regression' in them (a jaxlib layout change, a dtype change)
        must NEVER page — while the SAME shortfall under a rate key
        still does."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "update_4096_k32_peak_hbm_bytes": 2.0e8,
                "update_4096_k32_resident_handle_bytes": 1.3e8,
                "invert_4096_spread_pct": 1.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "update_4096_k32_peak_hbm_bytes": 2.0e9,
                "update_4096_k32_resident_handle_bytes": 1.3e9,
                "invert_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 0
        # The other way: the same 10x shortfall under a rate key pages.
        files = [
            _write(tmp_path, "r3.json", _round(10000.0, {
                "update_4096_k32_gflops": 2000.0,
                "update_4096_k32_spread_pct": 1.0})),
            _write(tmp_path, "r4.json", _round(10000.0, {
                "update_4096_k32_gflops": 200.0,
                "update_4096_k32_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 2
        assert check_bench.is_accounting_key(
            "update_4096_k32_peak_hbm_bytes")
        assert check_bench.is_accounting_key(
            "update_4096_k32_resident_handle_bytes")
        assert check_bench.is_accounting_key("invert_4096_xla_gflops")
        assert not check_bench.is_accounting_key(
            "update_4096_k32_gflops")
        keys = check_bench.comparable_keys(
            {"metric": "m", "value": 1.0,
             "extra": {"update_4096_k32_peak_hbm_bytes": 1.0,
                       "update_4096_k32_gflops": 9000.0}})
        assert "update_4096_k32_gflops" in keys
        assert "update_4096_k32_peak_hbm_bytes" not in keys

    def test_ckpt_cadence_accounting_class_never_compared(
            self, tmp_path):
        """ISSUE 20 satellite, trapped both ways: the checkpoint
        row's ``*_cadence`` knob (and its ``*_bytes`` snapshot size)
        are accounting-class — a cadence retune or a snapshot-layout
        change re-prices the SAME sweep and must NEVER page — while
        the same shortfall in the row's ``*_gflops`` overhead rate
        still does."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "ckpt_overhead_4096_cadence": 8,
                "ckpt_overhead_4096_bytes": 6.7e7,
                "invert_4096_spread_pct": 1.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "ckpt_overhead_4096_cadence": 1,
                "ckpt_overhead_4096_bytes": 6.7e8,
                "invert_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 0
        # The other way: the same shortfall under the rate key pages.
        files = [
            _write(tmp_path, "r3.json", _round(10000.0, {
                "ckpt_overhead_4096_gflops": 9000.0,
                "ckpt_overhead_4096_spread_pct": 1.0})),
            _write(tmp_path, "r4.json", _round(10000.0, {
                "ckpt_overhead_4096_gflops": 900.0,
                "ckpt_overhead_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 2
        assert check_bench.is_accounting_key(
            "ckpt_overhead_4096_cadence")
        assert check_bench.is_accounting_key(
            "ckpt_overhead_4096_bytes")
        assert not check_bench.is_accounting_key(
            "ckpt_overhead_4096_gflops")
        keys = check_bench.comparable_keys(
            {"metric": "m", "value": 1.0,
             "extra": {"ckpt_overhead_4096_cadence": 8.0,
                       "ckpt_overhead_4096_gflops": 9000.0}})
        assert "ckpt_overhead_4096_gflops" in keys
        assert "ckpt_overhead_4096_cadence" not in keys

    def test_update_rows_trap_quiet_regression(self, tmp_path):
        """ISSUE 12 satellite: the new resident-update keys
        (update_4096_k32_gflops / update_resident_amortized_gflops)
        participate in the sentinel — a quiet 30% shortfall on either
        pages (exit 2), exactly like the invert rows."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "update_4096_k32_gflops": 500.0,
                "update_4096_k32_spread_pct": 2.0,
                "update_resident_amortized_gflops": 300.0,
                "update_resident_amortized_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "update_4096_k32_gflops": 340.0,
                "update_4096_k32_spread_pct": 2.0,
                "update_resident_amortized_gflops": 300.0,
                "update_resident_amortized_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 2
        files[1] = _write(tmp_path, "r2b.json", _round(10000.0, {
            "update_4096_k32_gflops": 500.0,
            "update_4096_k32_spread_pct": 2.0,
            "update_resident_amortized_gflops": 190.0,
            "update_resident_amortized_spread_pct": 2.0}))
        assert check_bench.main(files) == 2

    def test_update_rows_variance_and_unknown_rules_hold(self, tmp_path):
        """The variance discipline covers the update keys too: a noisy
        session explains its own dip; a round without spread stats is
        unknown, never paged — and the exact-stem spread lookup binds
        the update row's own stats, not a sibling's."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "update_4096_k32_gflops": 500.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "update_4096_k32_gflops": 300.0,
                "update_4096_k32_spread_pct": 28.0})),
        ]
        assert check_bench.main(files) == 0
        files[1] = _write(tmp_path, "r2b.json", _round(10000.0, {
            "update_4096_k32_gflops": 300.0}))
        assert check_bench.main(files) == 0
        row = {"extra": {"update_4096_k32_spread_pct": 3.0,
                         "invert_4096_spread_pct": 44.0}}
        spread, _ = check_bench._variance_context(
            "update_4096_k32_gflops", row)
        assert spread == 3.0

    def test_renamed_config_is_a_new_row(self, tmp_path):
        """A config migration renames its key (m256 vs m384): the
        sentinel never diffs different configurations."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "invert_8192x8192_f32_m384_gflops": 14000.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "invert_8192x8192_f32_m256_gflops": 5000.0,
                "invert_8192x8192_f32_m256_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 0


class TestStructure:
    def test_unreadable_latest_exits_1(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("not json")
        assert check_bench.main([str(bad)]) == 1

    def test_single_round_nothing_to_compare(self, tmp_path):
        files = [_write(tmp_path, "r1.json", _round(10000.0))]
        assert check_bench.main(files) == 0

    def test_failed_round_skipped_mid_trajectory(self, tmp_path):
        """A round whose bench crashed (no parseable row) is skipped;
        the comparison spans the usable rounds around it."""
        p = tmp_path / "r2.json"
        p.write_text(json.dumps({"rc": 1, "tail": "Traceback ..."}))
        files = [
            _write(tmp_path, "r1.json", _round(10000.0)),
            str(p),
            _write(tmp_path, "r3.json", _round(
                6000.0, {"invert_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 2

    def test_tail_fallback_parses_json_line(self, tmp_path):
        p = tmp_path / "r1.json"
        p.write_text(json.dumps({
            "rc": 0,
            "tail": "WARNING: noise\n" + json.dumps(_round(9000.0))}))
        row = check_bench.load_round(str(p))
        assert row["value"] == 9000.0

    def test_env_fingerprint_reported_as_context(self, tmp_path):
        rounds = [
            ("r1", _round(10000.0)),
            ("r2", _round(10000.0, {"env": {
                "jax": "0.4.37", "jaxlib": "0.4.36",
                "device_kind": "cpu", "device_count": 8,
                "host_cpu_count": 4}})),
        ]
        regs, warns, notes = check_bench.check_trajectory(rounds)
        assert not regs and not warns
        assert any("jax 0.4.37" in n for n in notes)
        # Missing env in old rows: unknown context, never a gate.
        regs2, _, notes2 = check_bench.check_trajectory(
            [("r1", _round(10000.0)), ("r2", _round(10000.0))])
        assert not regs2
        assert any("unknown" in n for n in notes2)


class TestCommSentinel:
    """ISSUE 14 satellite, trapped both ways: the distributed rows'
    ``*_comm_bytes`` accounting fields are never compared cross-round
    (a dtype/layout change re-prices the same solve), while a quiet
    ``*_comm_gbps`` RATE shortfall — the mesh bandwidth sentinel —
    pages exactly like a gflops one."""

    def test_comm_bytes_accounting_never_pages(self, tmp_path):
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "sharded_swapfree_2048_comm_bytes": 4.1e7,
                "invert_4096_spread_pct": 1.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "sharded_swapfree_2048_comm_bytes": 4.1e8,
                "invert_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 0
        assert check_bench.is_accounting_key(
            "sharded_swapfree_2048_comm_bytes")
        keys = check_bench.comparable_keys(
            {"metric": "m", "value": 1.0,
             "extra": {"sharded_swapfree_2048_comm_bytes": 4.1e7,
                       "sharded_swapfree_2048_comm_gbps": 3.5}})
        assert "sharded_swapfree_2048_comm_bytes" not in keys
        assert "sharded_swapfree_2048_comm_gbps" in keys

    def test_comm_gbps_quiet_shortfall_pages(self, tmp_path):
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "sharded_swapfree_2048_comm_gbps": 3.5,
                "sharded_swapfree_2048_comm_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "sharded_swapfree_2048_comm_gbps": 2.1,
                "sharded_swapfree_2048_comm_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 2

    def test_solve_sharded_gflops_quiet_regression_pages(self,
                                                         tmp_path):
        """ISSUE 15 satellite, trapped both ways (1/2): a quiet
        shortfall on the new ``solve_sharded_4096_k8_gflops`` rate key
        — low recorded spread on both ends — is the exit-2 class."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "solve_sharded_4096_k8_gflops": 120.0,
                "solve_sharded_4096_k8_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "solve_sharded_4096_k8_gflops": 80.0,
                "solve_sharded_4096_k8_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 2

    def test_solve_row_accounting_keys_never_page(self, tmp_path):
        """ISSUE 15 satellite, trapped both ways (2/2): the sharded
        row's ``*_comm_bytes`` (and the fori row's ``*_xla_flops``)
        are accounting-class — a 10x change never pages — while the
        ``*_comm_gbps`` twin and the ``solve_fori_8192_k8_gflops``
        rate page like any gflops shortfall."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "solve_sharded_4096_comm_bytes": 3.2e9,
                "solve_fori_8192_xla_flops": 1.1e12,
                "solve_fori_8192_k8_gflops": 50.0,
                "solve_fori_8192_k8_spread_pct": 1.5})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "solve_sharded_4096_comm_bytes": 3.2e8,
                "solve_fori_8192_xla_flops": 1.1e11,
                "solve_fori_8192_k8_gflops": 49.0,
                "solve_fori_8192_k8_spread_pct": 1.5})),
        ]
        assert check_bench.main(files) == 0
        assert check_bench.is_accounting_key(
            "solve_sharded_4096_comm_bytes")
        # Raw flop counts are not rate keys: never comparable at all.
        keys = check_bench.comparable_keys(
            {"metric": "m", "value": 1.0,
             "extra": {"solve_fori_8192_xla_flops": 1.1e12,
                       "solve_sharded_4096_comm_bytes": 3.2e9,
                       "solve_fori_8192_k8_gflops": 50.0}})
        assert "solve_fori_8192_xla_flops" not in keys
        assert "solve_sharded_4096_comm_bytes" not in keys
        assert "solve_fori_8192_k8_gflops" in keys
        files[1] = _write(tmp_path, "r2b.json", _round(10000.0, {
            "solve_sharded_4096_comm_gbps": 1.0,
            "solve_fori_8192_k8_gflops": 30.0,
            "solve_fori_8192_k8_spread_pct": 1.5}))
        files[0] = _write(tmp_path, "r1b.json", _round(10000.0, {
            "solve_sharded_4096_comm_gbps": 3.5,
            "solve_fori_8192_k8_gflops": 50.0,
            "solve_fori_8192_k8_spread_pct": 1.5}))
        assert check_bench.main(files) == 2

    def test_comm_gbps_variance_and_unknown_rules_hold(self, tmp_path):
        """A noisy session explains its own GB/s dip; a round without
        spread stats (the single-run subprocess leg) is unknown, never
        paged — and the exact-stem lookup binds the _gbps row's own
        spread key."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "sharded_swapfree_2048_comm_gbps": 3.5})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "sharded_swapfree_2048_comm_gbps": 2.1,
                "sharded_swapfree_2048_comm_spread_pct": 30.0})),
        ]
        assert check_bench.main(files) == 0
        files[1] = _write(tmp_path, "r2b.json", _round(10000.0, {
            "sharded_swapfree_2048_comm_gbps": 2.1}))
        assert check_bench.main(files) == 0
        row = {"extra": {"sharded_swapfree_2048_comm_spread_pct": 2.5}}
        spread, _ = check_bench._variance_context(
            "sharded_swapfree_2048_comm_gbps", row)
        assert spread == 2.5


class TestLookaheadSentinel:
    """ISSUE 16 satellite, trapped both ways: the probe-ahead rows'
    rate keys page on quiet shortfalls; the ``*_overlap_frac`` modeled
    headroom is accounting-class (a comm-model re-weighting re-prices
    the same schedule) and never pages."""

    def test_lookahead_gflops_quiet_regression_pages(self, tmp_path):
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "lookahead_4096_gflops": 5000.0,
                "lookahead_4096_spread_pct": 2.0,
                "solve_lookahead_sharded_4096_k8_gflops": 120.0,
                "solve_lookahead_sharded_4096_k8_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "lookahead_4096_gflops": 3200.0,
                "lookahead_4096_spread_pct": 2.0,
                "solve_lookahead_sharded_4096_k8_gflops": 118.0,
                "solve_lookahead_sharded_4096_k8_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 2

    def test_overlap_frac_accounting_never_pages(self, tmp_path):
        # A 10x overlap_frac change (re-weighted comm model) with flat
        # rates: exit 0 — while the same rows' gflops keys stay
        # comparable and a quiet solve-row shortfall still pages.
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "lookahead_4096_overlap_frac": 0.21,
                "solve_lookahead_sharded_4096_overlap_frac": 0.34,
                "solve_lookahead_sharded_4096_comm_bytes": 3.2e9,
                "lookahead_4096_gflops": 5000.0,
                "lookahead_4096_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "lookahead_4096_overlap_frac": 0.021,
                "solve_lookahead_sharded_4096_overlap_frac": 0.034,
                "solve_lookahead_sharded_4096_comm_bytes": 3.2e8,
                "lookahead_4096_gflops": 4980.0,
                "lookahead_4096_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 0
        assert check_bench.is_accounting_key(
            "lookahead_4096_overlap_frac")
        assert check_bench.is_accounting_key(
            "solve_lookahead_sharded_4096_overlap_frac")
        keys = check_bench.comparable_keys(
            {"metric": "m", "value": 1.0,
             "extra": {"lookahead_4096_overlap_frac": 0.21,
                       "lookahead_4096_gflops": 5000.0,
                       "solve_lookahead_sharded_4096_k8_gflops": 120.0}})
        assert "lookahead_4096_overlap_frac" not in keys
        assert "lookahead_4096_gflops" in keys
        assert "solve_lookahead_sharded_4096_k8_gflops" in keys
        files[1] = _write(tmp_path, "r2b.json", _round(10000.0, {
            "solve_lookahead_sharded_4096_k8_gflops": 80.0,
            "solve_lookahead_sharded_4096_k8_spread_pct": 2.0}))
        files[0] = _write(tmp_path, "r1b.json", _round(10000.0, {
            "solve_lookahead_sharded_4096_k8_gflops": 120.0,
            "solve_lookahead_sharded_4096_k8_spread_pct": 2.0}))
        assert check_bench.main(files) == 2


class TestWorkSentinel:
    """ISSUE 19 satellite, trapped both ways: the sharded rows'
    ``*_work_skew`` / ``*_ragged_penalty`` work-accounting fields are
    never compared cross-round (a layout/block-size change re-prices
    the same solve), while the same rows' rate keys still page on
    quiet shortfalls."""

    def test_work_accounting_never_pages(self, tmp_path):
        # A 10x skew/penalty change (different layout, same solve)
        # with flat rates: exit 0.
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "sharded_swapfree_2048_work_skew": 1.0,
                "sharded_swapfree_2048_ragged_penalty": 0.0,
                "solve_sharded_4096_k8_work_skew": 1.05,
                "solve_sharded_4096_k8_ragged_penalty": 0.02,
                "solve_sharded_4096_k8_gflops": 120.0,
                "solve_sharded_4096_k8_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "sharded_swapfree_2048_work_skew": 1.46,
                "sharded_swapfree_2048_ragged_penalty": 2.08,
                "solve_sharded_4096_k8_work_skew": 1.45,
                "solve_sharded_4096_k8_ragged_penalty": 1.93,
                "solve_sharded_4096_k8_gflops": 119.0,
                "solve_sharded_4096_k8_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 0
        assert check_bench.is_accounting_key(
            "sharded_swapfree_2048_work_skew")
        assert check_bench.is_accounting_key(
            "sharded_swapfree_2048_ragged_penalty")
        assert check_bench.is_accounting_key(
            "solve_sharded_4096_k8_work_skew")
        keys = check_bench.comparable_keys(
            {"metric": "m", "value": 1.0,
             "extra": {"solve_sharded_4096_k8_work_skew": 1.45,
                       "solve_sharded_4096_k8_ragged_penalty": 1.93,
                       "solve_sharded_4096_k8_gflops": 120.0}})
        assert "solve_sharded_4096_k8_work_skew" not in keys
        assert "solve_sharded_4096_k8_ragged_penalty" not in keys
        assert "solve_sharded_4096_k8_gflops" in keys

    def test_rates_still_page_beside_work_accounting(self, tmp_path):
        # The other way: flat accounting fields must not mask a quiet
        # rate shortfall on the same rows.
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "solve_sharded_4096_k8_work_skew": 1.45,
                "solve_sharded_4096_k8_gflops": 120.0,
                "solve_sharded_4096_k8_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "solve_sharded_4096_k8_work_skew": 1.45,
                "solve_sharded_4096_k8_gflops": 80.0,
                "solve_sharded_4096_k8_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 2


class TestServeMeshRows:
    """ISSUE 18 satellite, trapped both ways: the mesh-serve lane's
    ``*_lane_bytes`` capture fields are accounting-class — a 10x
    re-pricing (a jaxlib layout change, a projection-formula change)
    must NEVER page — and its plain context keys (occupancy, execute
    wall time, compile delta) are never rate-compared either; the SAME
    shortfall under a rate key still pages."""

    def test_lane_bytes_accounting_never_pages(self, tmp_path):
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "serve_mesh_4096_projected_lane_bytes": 7.1e7,
                "serve_mesh_4096_measured_lane_bytes": 9.0e7,
                "serve_mesh_4096_occupancy": 1,
                "serve_mesh_4096_execute_ms": 1500.0,
                "serve_mesh_4096_compiles_delta": 0,
                "invert_4096_spread_pct": 1.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "serve_mesh_4096_projected_lane_bytes": 7.1e8,
                "serve_mesh_4096_measured_lane_bytes": 9.0e8,
                "serve_mesh_4096_occupancy": 1,
                "serve_mesh_4096_execute_ms": 15000.0,
                "serve_mesh_4096_compiles_delta": 0,
                "invert_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 0
        assert check_bench.is_accounting_key(
            "serve_mesh_4096_projected_lane_bytes")
        assert check_bench.is_accounting_key(
            "serve_mesh_4096_measured_lane_bytes")
        keys = check_bench.comparable_keys(
            {"metric": "m", "value": 1.0,
             "extra": {"serve_mesh_4096_projected_lane_bytes": 1.0,
                       "serve_mesh_4096_measured_lane_bytes": 1.0,
                       "serve_mesh_4096_occupancy": 1,
                       "serve_mesh_4096_execute_ms": 1500.0,
                       "serve_mesh_4096_compiles_delta": 0}})
        assert not any(k.startswith("serve_mesh") for k in keys)

    def test_same_shortfall_under_rate_key_pages(self, tmp_path):
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "serve_mesh_4096_gbps": 30.0,
                "serve_mesh_4096_spread_pct": 1.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "serve_mesh_4096_gbps": 3.0,
                "serve_mesh_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 2


class TestLpqpRows:
    """ISSUE 17 satellites, trapped both ways: the multi-RHS blocking
    sweep's per-k rate keys and the batched-update amortization rate
    page on quiet shortfalls; the LP/QP driver context row (iteration
    counts, wall seconds, speedup factor, latencies) and the sweep's
    per-k accounting keys are never rate-compared."""

    def test_k_sweep_quiet_regression_pages(self, tmp_path):
        """A quiet shortfall on one leg of the k sweep
        (``solve_sharded_4096_k32_gflops``) is the exit-2 class — each
        block width is its own like-for-like key."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "solve_sharded_4096_k1_gflops": 60.0,
                "solve_sharded_4096_k1_spread_pct": 2.0,
                "solve_sharded_4096_k32_gflops": 140.0,
                "solve_sharded_4096_k32_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "solve_sharded_4096_k1_gflops": 59.0,
                "solve_sharded_4096_k1_spread_pct": 2.0,
                "solve_sharded_4096_k32_gflops": 90.0,
                "solve_sharded_4096_k32_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 2

    def test_k_sweep_accounting_and_variance_rules(self, tmp_path):
        """The sweep's per-k ``*_comm_bytes``/``*_xla_flops`` never
        page (accounting / raw counts); a per-k ``*_comm_gbps`` dip is
        explained by the leg's own spread via the fuzzy sibling
        lookup, and pages when the session was quiet."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "solve_sharded_4096_k32_comm_bytes": 3.4e9,
                "solve_sharded_4096_k32_xla_flops": 2.2e12,
                "solve_sharded_4096_k1_comm_gbps": 3.5,
                "solve_sharded_4096_k1_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "solve_sharded_4096_k32_comm_bytes": 3.4e8,
                "solve_sharded_4096_k32_xla_flops": 2.2e11,
                "solve_sharded_4096_k1_comm_gbps": 2.0,
                "solve_sharded_4096_k1_spread_pct": 30.0})),
        ]
        assert check_bench.main(files) == 0
        assert check_bench.is_accounting_key(
            "solve_sharded_4096_k32_comm_bytes")
        keys = check_bench.comparable_keys(
            {"metric": "m", "value": 1.0,
             "extra": {"solve_sharded_4096_k32_comm_bytes": 3.4e9,
                       "solve_sharded_4096_k32_xla_flops": 2.2e12,
                       "solve_sharded_4096_k1_comm_gbps": 3.5,
                       "solve_sharded_4096_k32_gflops": 140.0}})
        assert "solve_sharded_4096_k32_comm_bytes" not in keys
        assert "solve_sharded_4096_k32_xla_flops" not in keys
        assert "solve_sharded_4096_k1_comm_gbps" in keys
        assert "solve_sharded_4096_k32_gflops" in keys
        files[1] = _write(tmp_path, "r2b.json", _round(10000.0, {
            "solve_sharded_4096_k1_comm_gbps": 2.0,
            "solve_sharded_4096_k1_spread_pct": 2.0}))
        assert check_bench.main(files) == 2

    def test_update_batched_quiet_regression_pages(self, tmp_path):
        """ISSUE 17 satellite, trapped both ways (1/2): a quiet
        shortfall on ``update_batched_amortized_gflops`` — the batched
        update lane's warm amortized rate — is the exit-2 class."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "update_batched_amortized_gflops": 0.09,
                "update_batched_amortized_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "update_batched_amortized_gflops": 0.05,
                "update_batched_amortized_spread_pct": 2.0})),
        ]
        assert check_bench.main(files) == 2

    def test_update_batched_variance_explains_its_dip(self, tmp_path):
        """The tiny-launch row IS jittery on a shared CPU host — its
        own high spread (or variance_flag) must explain the dip."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "update_batched_amortized_gflops": 0.09,
                "update_batched_amortized_spread_pct": 2.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "update_batched_amortized_gflops": 0.05,
                "update_batched_amortized_spread_pct": 89.0,
                "update_batched_amortized_variance_flag":
                    "high_spread"})),
        ]
        assert check_bench.main(files) == 0

    def test_lp_demo_context_rows_never_page(self, tmp_path):
        """ISSUE 17 satellite, trapped both ways (2/2): the LP/QP
        driver context row is counts/seconds/speedups — none are rate
        keys, so a halved iteration count or a sub-1.0 speedup factor
        (recorded, per the ISSUE, even when < 1) never pages."""
        files = [
            _write(tmp_path, "r1.json", _round(10000.0, {
                "lp_demo_iters": 120, "lp_demo_seconds": 0.4,
                "lp_demo_iters_per_s": 300.0,
                "update_batched_speedup_x": 2.5,
                "update_batched_one_per_launch_ms": 0.36,
                "update_batched_amortized_ms": 0.14,
                "invert_4096_spread_pct": 1.0})),
            _write(tmp_path, "r2.json", _round(10000.0, {
                "lp_demo_iters": 60, "lp_demo_seconds": 4.0,
                "lp_demo_iters_per_s": 15.0,
                "update_batched_speedup_x": 0.8,
                "update_batched_one_per_launch_ms": 0.36,
                "update_batched_amortized_ms": 0.45,
                "invert_4096_spread_pct": 1.0})),
        ]
        assert check_bench.main(files) == 0
        keys = check_bench.comparable_keys(
            {"metric": "m", "value": 1.0,
             "extra": {"lp_demo_iters": 120,
                       "lp_demo_iters_per_s": 300.0,
                       "lp_demo_seconds": 0.4,
                       "update_batched_speedup_x": 2.5,
                       "update_batched_amortized_ms": 0.14,
                       "update_batched_amortized_gflops": 0.09}})
        assert keys == {"m": 1.0,
                        "update_batched_amortized_gflops": 0.09}
