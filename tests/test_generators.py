import jax.numpy as jnp
import numpy as np

from tpu_jordan.ops import generate


def test_absdiff_matches_reference_formula():
    # f(i,j) = |i-j| (main.cpp:47-57)
    a = np.asarray(generate("absdiff", (5, 5), jnp.float64))
    expect = np.abs(np.subtract.outer(np.arange(5), np.arange(5)))
    np.testing.assert_array_equal(a, expect)


def test_hilbert_matches_reference_formula():
    # 1/(i+j+1) (main.cpp:49-51)
    a = np.asarray(generate("hilbert", (4, 4), jnp.float64))
    i, j = np.meshgrid(np.arange(4), np.arange(4), indexing="ij")
    np.testing.assert_allclose(a, 1.0 / (i + j + 1), rtol=1e-14)


def test_identity_generator():
    a = np.asarray(generate("identity", (6, 6), jnp.float32))
    np.testing.assert_array_equal(a, np.eye(6, dtype=np.float32))


def test_offsets_give_shard_views():
    # a shard generated with offsets equals the corresponding window of the
    # full matrix — the no-comm per-shard init path (init_matrix analog)
    full = np.asarray(generate("absdiff", (8, 8), jnp.float64))
    shard = np.asarray(
        generate("absdiff", (2, 8), jnp.float64, row_offset=3, col_offset=0)
    )
    np.testing.assert_array_equal(shard, full[3:5])


def test_rand_uniform_deterministic_and_bounded():
    import numpy as np
    import jax.numpy as jnp

    from tpu_jordan.ops import generate

    a = np.asarray(generate("rand", (64, 64), jnp.float32))
    b = np.asarray(generate("rand", (64, 64), jnp.float32))
    np.testing.assert_array_equal(a, b)               # stateless hash
    assert (-1.0 <= a).all() and (a < 1.0).all()
    # Not degenerate: decent spread and no constant rows/cols.
    assert a.std() > 0.4
    assert np.abs(a.mean()) < 0.1
    # Windowed generation matches the global matrix (shard-local parity).
    w = np.asarray(generate("rand", (16, 16), jnp.float32,
                            row_offset=8, col_offset=24))
    np.testing.assert_array_equal(w, a[8:24, 24:40])


def test_rand_uniform_inverts():
    import numpy as np
    import jax.numpy as jnp

    from tpu_jordan.driver import solve

    res = solve(96, 32, generator="rand", workers=4)
    # Unnormalized residual; ‖A‖∞ ≈ n/2 for uniform [-1,1) entries, and a
    # random 96² matrix can carry κ ~ 1e3-1e4 at fp32.
    assert res.residual / 48 < 5e-3
    from tpu_jordan.ops import generate

    a = np.asarray(generate("rand", (96, 96), jnp.float32))
    np.testing.assert_allclose(np.asarray(res.inverse), np.linalg.inv(a),
                               rtol=5e-2, atol=1e-2)
