"""Request-journey tracing, flight recorder, and SLO burn-rate units
(ISSUE 8): deterministic request ids and hop/terminal semantics, the
bounded always-on recorder's drop accounting and slice brackets,
multi-window burn-rate math on a fake clock, the ``to_json_line``
collision guard, async journey lanes in Chrome traces (accept +
doctored-reject), the check_slo / check_blackbox both-ways gates, and
the service-level pins: every direct submit journeys to a terminal
result, typed rejections explain themselves, and the warm-serve
zero-compile/zero-measurement contract holds with the recorder ON
(it is never off)."""

import importlib.util
import json
import pathlib

import numpy as np
import pytest

from tpu_jordan.obs import journey as journey_mod
from tpu_jordan.obs.export import to_chrome_trace, to_json_line
from tpu_jordan.obs.journey import (JourneyLog, async_trace_events,
                                    outcome_ledger)
from tpu_jordan.obs.metrics import REGISTRY, MetricsRegistry
from tpu_jordan.obs.recorder import RECORDER, FlightRecorder
from tpu_jordan.obs.slo import SLOMonitor, SLOSpec, bucket_specs

_tools = pathlib.Path(__file__).resolve().parent.parent / "tools"


def _load(name):
    spec = importlib.util.spec_from_file_location(name, _tools / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


check_blackbox = _load("check_blackbox")
check_slo = _load("check_slo")
check_telemetry = _load("check_telemetry")


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


def _log(clock=None):
    """A private journey log writing into a private recorder — unit
    tests never depend on (or pollute) the process-wide ring."""
    clock = clock if clock is not None else FakeClock()
    rec = FlightRecorder(capacity=256, clock=clock)
    return JourneyLog(prefix="t", clock=clock, recorder=rec), rec, clock


class TestRequestContext:
    def test_deterministic_ids_in_submit_order(self):
        log, _, _ = _log()
        first = log.new(16, 16).request_id
        base = first[:first.index("-")]
        ids = [first] + [log.new(16, 16).request_id for _ in range(2)]
        assert ids == [f"{base}-{i:05d}" for i in (1, 2, 3)]
        # A SECOND log with the same requested prefix mints a distinct
        # instance prefix: whole-ring exports group purely by
        # request_id, so ids must never collide across a run's
        # successive services/fleets (two req-00001 lanes would merge
        # two different requests into one journey).
        log2, _, _ = _log()
        rid2 = log2.new(16, 16).request_id
        assert rid2.endswith("-00001") and rid2 != ids[0]

    def test_hops_mirror_into_recorder_with_same_timestamp(self):
        log, rec, clock = _log()
        ctx = log.new(17, 32)
        clock.advance(1.5)
        ctx.event("route", replica="r0g1", slot=0)
        evs = rec.events(kind="journey")
        assert [e["event"] for e in evs] == ["submit", "route"]
        assert evs[1]["t"] == 1.5 and evs[1]["request_id"] == ctx.request_id
        assert evs[1]["replica"] == "r0g1"
        # The context's own view carries the SAME instant.
        assert ctx.events()[1]["t"] == 1.5

    def test_close_is_idempotent_and_feeds_slo_series(self):
        out = REGISTRY.counter("tpu_jordan_request_outcome_total")
        before = out.value(outcome="error", bucket=32)
        log, rec, clock = _log()
        ctx = log.new(30, 32)
        clock.advance(0.25)
        ctx.close("error", error="DeadlineExceededError")
        ctx.close("ok")                      # late race: first close won
        ctx.event("late_hop")                # after close: dropped
        assert ctx.outcome() == ("error", "DeadlineExceededError")
        assert [e["event"] for e in ctx.events()] == ["submit", "result"]
        assert out.value(outcome="error", bucket=32) == before + 1
        assert log.active_count() == 0
        assert log.ledger()["typed_errors"] == {
            "DeadlineExceededError": 1}

    def test_event_cap_bounds_pathological_journeys(self, monkeypatch):
        monkeypatch.setattr(journey_mod, "MAX_EVENTS_PER_REQUEST", 4)
        log, _, _ = _log()
        ctx = log.new(16, 16)
        for i in range(10):
            ctx.event("hop", i=i)
        assert len(ctx.events()) == 4        # submit + 3 hops, capped

    def test_close_from_future_maps_outcomes(self):
        from concurrent.futures import Future

        log, _, _ = _log()
        ok, bad = Future(), Future()
        ok.set_result(type("R", (), {"singular": True})())
        bad.set_exception(ValueError("boom"))
        c1, c2 = log.new(16, 16), log.new(16, 16)
        c1.close_from_future(ok)
        c2.close_from_future(bad)
        assert c1.outcome() == ("ok", None)
        assert c1.events()[-1]["singular"] is True
        assert c2.outcome() == ("error", "ValueError")


class TestLedgerAndLanes:
    def _events(self):
        log, rec, clock = _log()
        a, b, c = log.new(16, 16), log.new(16, 16), log.new(16, 16)
        clock.advance(0.1)
        a.event("dispatch", cause="full")
        a.close("ok")
        b.event("shed", reason="dead")
        b.close("error", error="ReplicaKilledError")
        # c never closes: the gap.
        return rec.events(), (a, b, c)

    def test_outcome_ledger_counts_ok_typed_and_gaps(self):
        events, (a, b, c) = self._events()
        led = outcome_ledger(events)
        assert led["submitted"] == 3 and led["ok"] == 1
        assert led["typed_errors"] == {"ReplicaKilledError": 1}
        assert led["gaps"] == [c.request_id]

    def test_async_lanes_one_per_request(self):
        events, (a, b, c) = self._events()
        lanes = async_trace_events(events)
        by_ph = {}
        for e in lanes:
            by_ph.setdefault(e["ph"], []).append(e)
        assert {e["id"] for e in by_ph["b"]} == {
            a.request_id, b.request_id, c.request_id}
        assert len(by_ph["b"]) == len(by_ph["e"]) == 3
        # Every hop is an instant inside its lane, ts in microseconds.
        shed = next(e for e in by_ph["n"] if e["name"] == "shed")
        assert shed["id"] == b.request_id
        assert shed["args"]["reason"] == "dead"
        assert shed["ts"] == pytest.approx(0.1 * 1e6)

    def test_explanatory_hops_match_checker_copy(self):
        """The checkers duplicate EXPLANATORY_HOPS (no jax import);
        this pin is what keeps the two sets from drifting."""
        assert (journey_mod.EXPLANATORY_HOPS
                == check_blackbox.EXPLANATORY_HOPS)


class TestFlightRecorder:
    def test_ring_bounded_with_explicit_drop_accounting(self):
        rec = FlightRecorder(capacity=8, clock=FakeClock())
        for i in range(20):
            rec.record("tick", i=i)
        assert rec.total == 20
        evs = rec.events()
        assert len(evs) == 8 and evs[0]["i"] == 12
        dump = rec.dump()
        assert dump["retained"] == 8 and dump["dropped"] == 12
        assert dump["recorded_total"] == 20

    def test_since_brackets_exactly_one_operation(self):
        rec = FlightRecorder(capacity=64, clock=FakeClock())
        rec.record("before")
        mark = rec.total
        rec.record("inside", x=1)
        rec.record("inside", x=2)
        sliced = rec.since(mark)
        assert [e["x"] for e in sliced] == [1, 2]
        assert rec.dump(events=sliced)["dropped"] == 0

    def test_write_is_one_json_document(self, tmp_path):
        rec = FlightRecorder(capacity=8, clock=FakeClock())
        rec.record("kill", slot=1)
        path = tmp_path / "bb.json"
        rec.write(str(path))
        doc = json.loads(path.read_text())
        assert doc["metric"] == "blackbox"
        assert doc["events"][0]["kind"] == "kill"

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            FlightRecorder(capacity=0)


def _slo_fixture():
    """A private registry + fake clock the monitor samples: the test
    scripts traffic by bumping the outcome counter between samples."""
    reg = MetricsRegistry()
    clock = FakeClock()
    c = reg.counter("tpu_jordan_request_outcome_total")
    h = reg.histogram("tpu_jordan_request_latency_seconds")
    return reg, clock, c, h


class TestSLOMonitor:
    def test_healthy_traffic_burns_zero(self):
        reg, clock, c, _ = _slo_fixture()
        mon = SLOMonitor([SLOSpec(name="s", availability=0.9)],
                         registry=reg, clock=clock,
                         windows=((100.0, 10.0, 2.0),))
        mon.sample()
        c.inc(40, outcome="ok", bucket="16")
        clock.advance(50.0)
        mon.sample()
        rep = mon.evaluate()
        (pair,) = rep["objectives"][0]["windows"]
        assert pair["long"]["requests"] == 40
        assert pair["long"]["burn_rate"] == 0.0
        assert pair["page"] is False and rep["healthy"] is True

    def test_page_requires_long_and_short_window_agreement(self):
        reg, clock, c, _ = _slo_fixture()
        mon = SLOMonitor([SLOSpec(name="s", availability=0.9)],
                         registry=reg, clock=clock,
                         windows=((1000.0, 10.0, 2.0),))
        mon.sample()                           # t=0: clean
        clock.advance(500.0)
        c.inc(5, outcome="ok", bucket="16")
        c.inc(5, outcome="error", bucket="16")  # a burst: rate 0.5
        mon.sample()                           # t=500
        clock.advance(95.0)
        c.inc(20, outcome="ok", bucket="16")   # recovered since
        mon.sample()                           # t=595
        rep = mon.evaluate()
        (pair,) = rep["objectives"][0]["windows"]
        # Long window (truncated to the whole run): 5 errors / 30,
        # burn 1.67 under threshold... craft it hot instead:
        assert pair["long"]["errors"] == 5
        assert pair["short"]["errors"] == 0    # the burst is OVER
        assert pair["page"] is False           # short window vetoes

    def test_page_fires_when_both_windows_burn(self):
        reg, clock, c, _ = _slo_fixture()
        mon = SLOMonitor([SLOSpec(name="s", availability=0.9)],
                         registry=reg, clock=clock,
                         windows=((100.0, 10.0, 2.0),))
        mon.sample()
        clock.advance(95.0)
        c.inc(10, outcome="ok", bucket="16")
        c.inc(30, outcome="error", bucket="16")
        mon.sample()
        rep = mon.evaluate()
        (pair,) = rep["objectives"][0]["windows"]
        assert pair["long"]["burn_rate"] == pytest.approx(7.5)
        assert pair["page"] is True
        assert rep["objectives"][0]["paging"] is True
        assert rep["healthy"] is False

    def test_bucket_filter_and_p99_objective(self):
        reg, clock, c, h = _slo_fixture()
        c.inc(10, outcome="ok", bucket="16")
        for v in (0.01,) * 9 + (0.5,):
            h.observe(v, bucket="16")
        mon = SLOMonitor(
            [SLOSpec(name="lat", bucket="16", availability=0.9,
                     p99_latency_ms=100.0)],
            registry=reg, clock=clock, windows=((100.0, 10.0, 2.0),))
        mon.sample()
        clock.advance(1.0)
        mon.sample()
        obj = mon.evaluate()["objectives"][0]
        assert obj["p99_ms"] == pytest.approx(500.0)
        assert obj["p99_ok"] is False          # 500 ms > the 100 ms SLO
        assert obj["paging"] is False
        assert obj["healthy"] is False

    def test_spec_and_window_validation(self):
        with pytest.raises(ValueError):
            SLOSpec(name="impossible", availability=1.0)
        with pytest.raises(ValueError):
            SLOMonitor([SLOSpec(name="s")], windows=((10.0, 20.0, 1.0),))
        with pytest.raises(ValueError):
            SLOMonitor([])

    def test_bucket_specs_rollup(self):
        specs = bucket_specs([64, 16], availability=0.99)
        assert [s.name for s in specs] == ["fleet", "bucket_16",
                                           "bucket_64"]
        assert specs[0].bucket is None and specs[1].bucket == "16"


class TestToJsonLineCollision:
    """ISSUE 8 satellite: caller extras can no longer silently clobber
    the payload keys ``to_json_line`` owns."""

    def test_colliding_extra_is_typed_usage_error(self):
        from tpu_jordan.driver import UsageError

        with pytest.raises(UsageError, match="collide"):
            to_json_line(registry=REGISTRY, metrics={"doctored": 1})
        with pytest.raises(UsageError, match="metric"):
            to_json_line(metric="not_telemetry")

    def test_non_colliding_extras_pass_through(self):
        doc = json.loads(to_json_line(registry=REGISTRY, run_id="r1"))
        assert doc["metric"] == "telemetry" and doc["run_id"] == "r1"
        assert "tpu_jordan_request_outcome_total" in doc["metrics"]


class TestJourneyLanesInChromeTrace:
    """The async journey view rides ``to_chrome_trace`` and must pass
    the SAME checker ``make metrics-demo`` runs — accept AND
    doctored-reject (the repo's both-ways checker discipline)."""

    def _trace(self):
        log, rec, clock = _log()
        ctx = log.new(16, 16)
        clock.advance(0.01)
        ctx.event("dispatch", cause="full")
        clock.advance(0.01)
        ctx.close("ok")
        return to_chrome_trace(None, journey_events=rec.events())

    def test_journeys_only_trace_accepted(self):
        doc = self._trace()
        assert check_telemetry.check_chrome_trace(
            json.dumps(doc), "<test>") == len(doc["traceEvents"])

    def test_doctored_traces_rejected(self):
        # An instant pushed outside its lane's bracket.
        doc = self._trace()
        n = next(e for e in doc["traceEvents"] if e["ph"] == "n")
        n["ts"] = 1e9
        with pytest.raises(AssertionError, match="outside lane"):
            check_telemetry.check_chrome_trace(json.dumps(doc), "<t>")
        # An async event with no lane id.
        doc = self._trace()
        next(e for e in doc["traceEvents"]
             if e["ph"] == "b").pop("id")
        with pytest.raises(AssertionError, match="without an id"):
            check_telemetry.check_chrome_trace(json.dumps(doc), "<t>")
        # An unbalanced lane (e dropped).
        doc = self._trace()
        doc["traceEvents"] = [e for e in doc["traceEvents"]
                              if e["ph"] != "e"]
        with pytest.raises(AssertionError, match="unbalanced"):
            check_telemetry.check_chrome_trace(json.dumps(doc), "<t>")
        # A request lane with no hop instants explains nothing.
        doc = self._trace()
        doc["traceEvents"] = [e for e in doc["traceEvents"]
                              if e["ph"] != "n"]
        with pytest.raises(AssertionError, match="no hop"):
            check_telemetry.check_chrome_trace(json.dumps(doc), "<t>")


class TestCheckSLO:
    def _report(self):
        reg, clock, c, _ = _slo_fixture()
        mon = SLOMonitor([SLOSpec(name="s", availability=0.9)],
                         registry=reg, clock=clock,
                         windows=((100.0, 10.0, 2.0),))
        mon.sample()
        c.inc(18, outcome="ok", bucket="16")
        c.inc(2, outcome="error", bucket="16")
        clock.advance(50.0)
        mon.sample()
        return mon.evaluate()

    def test_real_report_accepted(self):
        errs, paging = check_slo.check(self._report())
        assert errs == [] and paging is False
        wrapped = {"metric": "fleet_demo", "slo": self._report()}
        assert check_slo.check(wrapped) == ([], False)

    def test_doctored_reports_rejected(self):
        rep = self._report()
        rep["objectives"][0]["windows"][0]["long"]["burn_rate"] = 0.0
        errs, _ = check_slo.check(rep)
        assert any("burn_rate" in e for e in errs)

        rep = self._report()
        rep["objectives"][0]["windows"][0]["page"] = True
        errs, _ = check_slo.check(rep)
        assert any("multi-window AND" in e for e in errs)

        rep = self._report()
        rep["healthy"] = False                 # contradicts objectives
        errs, _ = check_slo.check(rep)
        assert any("contradicts the AND" in e for e in errs)

        errs, _ = check_slo.check({"metric": "nope"})
        assert any("not an slo_report" in e for e in errs)

    def test_paging_report_is_consistent_not_invalid(self):
        reg, clock, c, _ = _slo_fixture()
        mon = SLOMonitor([SLOSpec(name="s", availability=0.9)],
                         registry=reg, clock=clock,
                         windows=((100.0, 10.0, 2.0),))
        mon.sample()
        c.inc(30, outcome="error", bucket="16")
        clock.advance(50.0)
        mon.sample()
        errs, paging = check_slo.check(mon.evaluate())
        assert errs == [] and paging is True


class TestCheckBlackbox:
    """The causal-chain rules over a black-box slice — accept on a
    real-shaped event stream, reject every doctored break."""

    def _events(self):
        log, rec, clock = _log()
        # A clean request.
        a = log.new(16, 16)
        a.event("route", replica="r0g1", slot=0)
        a.close("ok")
        # An injected kill -> death -> restart chain, with the victim's
        # request rerouted and finally typed.
        rec.record("fault_injected", point="replica_kill", call=3,
                   mode="permanent")
        rec.record("replica_death", replica="r1g1", slot=1,
                   reason="injected")
        b = log.new(16, 16)
        b.event("route", replica="r1g1", slot=1)
        b.event("requeue", from_replica="r1g1", attempt=1)
        b.event("shed", reason="dead", replica="r1g1")
        b.close("error", error="ReplicaKilledError")
        rec.record("restart", slot=1, replica="r1g2", cause="death")
        self._typed_rid = b.request_id
        return rec.events()

    def test_real_slice_accepted(self):
        events = self._events()
        bb = {"dropped": 0, "events": events}
        assert check_blackbox.check_journeys(bb, requests=2) == []
        assert check_blackbox.check_fault_chains(events) == []
        assert check_blackbox.check_death_coverage(events) == []
        led = check_blackbox.ledger(events)
        assert led["ok"] == 1 and led["typed_errors"] == {
            "ReplicaKilledError": 1}
        errs, warnings = check_blackbox.check_dump(
            {"metric": "blackbox", "dropped": 0, "retained": len(events),
             "events": events})
        assert errs == [] and warnings == []

    def test_gap_and_causal_breaks_rejected(self):
        # A journey that never resolves.
        events = [e for e in self._events()
                  if not (e.get("request_id") == self._typed_rid
                          and e.get("event") == "result")]
        errs = check_blackbox.check_journeys(
            {"dropped": 0, "events": events}, requests=2)
        assert any("never resolved" in e for e in errs)

        # A typed failure with no explanatory hop.
        events = [e for e in self._events()
                  if e.get("event") not in ("requeue", "shed")]
        errs = check_blackbox.check_journeys(
            {"dropped": 0, "events": events}, requests=2)
        assert any("NO explanatory hop" in e for e in errs)

        # A kill whose death was never recorded.
        events = [e for e in self._events()
                  if e.get("kind") != "replica_death"]
        assert any("causal chain is broken" in e
                   for e in check_blackbox.check_fault_chains(events))

        # A death no restart/withholding ever covered.
        events = [e for e in self._events()
                  if e.get("kind") != "restart"]
        assert any("supervision chain" in e
                   for e in check_blackbox.check_death_coverage(events))

        # A window that overflowed cannot prove reconstruction.
        errs = check_blackbox.check_journeys(
            {"dropped": 3, "events": self._events()}, requests=2)
        assert any("gaps" in e for e in errs)

        # A ledger that disagrees with its own events is drift.
        errs = check_blackbox.reconcile_ledgers(
            {"submitted": 99}, self._events())
        assert any("drift" in e for e in errs)

    def test_missing_requests_detected(self):
        errs = check_blackbox.check_journeys(
            {"dropped": 0, "events": self._events()}, requests=5)
        assert any("left no trail" in e for e in errs)


class TestServiceJourneys:
    """Service-level integration (fast, tiny buckets): direct submits
    journey to a terminal result with the enqueue/dispatch/executor/
    served path recorded, typed rejections explain themselves, and the
    warm path stays free with the recorder on."""

    def test_direct_submit_journeys_to_terminal_ok(self, rng):
        from tpu_jordan.serve import JordanService

        with JordanService(batch_cap=4, max_wait_ms=1.0) as svc:
            svc.warmup(shapes=[16])
            futs = [svc.submit(rng.standard_normal(
                (16, 16)).astype(np.float32)) for _ in range(4)]
            [f.result(60) for f in futs]
            # Done callbacks run on the dispatcher thread right after
            # set_result; close() lands before contexts() is read only
            # once the callback fires — poll briefly for the race.
            import time
            deadline = time.monotonic() + 5
            while (svc.journey.ledger()["ok"] < 4
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        led = svc.journey.ledger()
        assert led["ok"] == 4 and led["gaps"] == []
        ctx = svc.journey.contexts()[0]
        names = [e["event"] for e in ctx.events()]
        for hop in ("submit", "enqueue", "dispatch", "executor",
                    "served", "result"):
            assert hop in names, f"{hop} missing from {names}"
        # The executor hop records compile-vs-cache-hit per request.
        ex = next(e for e in ctx.events() if e["event"] == "executor")
        assert ex["source"] in ("cached", "compiled", "shared_store")

    def test_overload_rejection_journeys_typed(self, rng):
        from tpu_jordan.serve import JordanService
        from tpu_jordan.serve.batcher import ServiceOverloadedError

        svc = JordanService(batch_cap=1, max_queue=1, autostart=False)
        try:
            svc.warmup(shapes=[16])
            mats = [rng.standard_normal((16, 16)).astype(np.float32)
                    for _ in range(3)]
            svc.submit(mats[0])
            with pytest.raises(ServiceOverloadedError):
                for a in mats[1:]:
                    svc.submit(a)
        finally:
            svc.start()
            svc.close()
        rejected = [c for c in svc.journey.contexts()
                    if (c.outcome() or ("", ""))[0] == "error"]
        assert len(rejected) == 1
        names = [e["event"] for e in rejected[0].events()]
        assert "reject" in names               # the explanatory hop
        assert rejected[0].outcome() == ("error",
                                         "ServiceOverloadedError")

    def test_warm_serve_stays_free_with_recorder_on(self, rng):
        """ISSUE 8 satellite: the recorder has no off switch, so the
        warm-path pins must hold WITH it recording — zero compiles,
        zero measurements, bounded ring — while the journey events for
        the burst demonstrably landed in the ring."""
        from tpu_jordan.serve import JordanService

        with JordanService(batch_cap=4, max_wait_ms=1.0) as svc:
            svc.warmup(shapes=[16])
            compiles = REGISTRY.counter("tpu_jordan_compiles_total")
            measures = REGISTRY.counter(
                "tpu_jordan_tuner_measurements_total")
            c0, m0, r0 = compiles.total(), measures.total(), RECORDER.total
            futs = [svc.submit(rng.standard_normal(
                (16, 16)).astype(np.float32)) for _ in range(20)]
            assert all(not f.result(60).singular for f in futs)
            assert compiles.total() == c0      # zero compiles
            assert measures.total() == m0      # zero measurements
            assert RECORDER.total > r0         # ...and it WAS recording
            assert len(RECORDER.events()) <= RECORDER.capacity
