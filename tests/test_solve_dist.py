"""Distributed solve (ISSUE 15): the [A | B] elimination sharded over
the 1D/2D meshes plus the fori solve engine that lifts MAX_UNROLL_NR.

Parity discipline (the house style): cross-program pins (distributed vs
single-device) run float64 fixtures — BIT-EXACT on block-aligned sizes
(n % m == 0, where the two XLA programs provably compute identical op
sequences; pinned), tight allclose on ragged ones (identity-pad
constant-folding reorders XLA reductions at the ulp level — the same
caveat the invert parity suite carries); same-family pins (unrolled vs
fori, 1D flavor vs 1D flavor) are bitwise everywhere."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.driver import UsageError
from tpu_jordan.linalg import solve_system
from tpu_jordan.linalg.engine import (block_jordan_solve,
                                      block_jordan_solve_fori)
from tpu_jordan.ops import generate


def _fixture(n, k, dtype=jnp.float64, gen="rand"):
    a = generate(gen, (n, n), dtype)
    b = generate("crand" if jnp.dtype(dtype).kind == "c" else "rand",
                 (n, k), dtype, row_offset=n)
    return a, b


class TestDistributedSolveParity:
    @pytest.mark.smoke      # the distributed-solve engine-parity case
    def test_1d_p2_bitmatches_single_device(self):
        a, b = _fixture(48, 3)
        x_ref, s_ref = block_jordan_solve(a, b, block_size=8)
        res = solve_system(a, b, block_size=8, workers=2)
        # Auto ranks the probe-ahead flavor first since ISSUE 16 (the
        # hidden-probe saving); the bits must be the base engine's.
        assert res.engine == "solve_lookahead"
        assert bool(s_ref) is False and res.singular is False
        assert np.array_equal(np.asarray(res.x), np.asarray(x_ref)), \
            "1D distributed solve diverged bitwise from single-device"
        base = solve_system(a, b, block_size=8, workers=2,
                            engine="solve_sharded")
        assert base.engine == "solve_sharded"
        assert np.array_equal(np.asarray(base.x), np.asarray(res.x)), \
            "probe-ahead 1D solve diverged bitwise from solve_sharded"

    @pytest.mark.slow  # tier-1 budget: test_1d_p2_bitmatches_single_device stays
    def test_1d_tied_pivots_bitmatch(self):
        # |i-j| has exactly-repeated candidate blocks: the composite-key
        # pmin must reproduce argmin's lowest-global-row tie rule.
        a, b = _fixture(64, 2, gen="absdiff")
        x_ref, _ = block_jordan_solve(a, b, block_size=8)
        res = solve_system(a, b, block_size=8, workers=4)
        assert np.array_equal(np.asarray(res.x), np.asarray(x_ref))

    @pytest.mark.slow  # tier-1 budget: comm's ragged-solve reconciliation covers the fast run
    def test_ragged_n_k1_edge(self):
        # Ragged n (identity-pad tail mid-block) + the thinnest RHS:
        # unrolled and fori distributed flavors stay BITWISE equal;
        # vs the single-device engine the pin is tight allclose (see
        # module docstring).
        from tpu_jordan.parallel import make_mesh
        from tpu_jordan.parallel.layout import CyclicLayout
        from tpu_jordan.parallel.ring_gemm import (
            _to_identity_padded_blocks)
        from tpu_jordan.parallel.sharded_inplace import (
            compile_sharded_jordan_solve, gather_solution_1d,
            scatter_rhs_1d)

        n, m, p = 45, 8, 4
        a, b = _fixture(n, 1)
        x_ref, _ = block_jordan_solve(a, b, block_size=m)
        mesh = make_mesh(p)
        lay = CyclicLayout.create(n, m, p)
        W = _to_identity_padded_blocks(a, lay, mesh)
        X = scatter_rhs_1d(b, lay, mesh)
        outs = []
        for unroll in (True, False):
            run = compile_sharded_jordan_solve(W, X, mesh, lay,
                                               unroll=unroll)
            xb, sing = run(W, X)
            assert not bool(sing.any())
            outs.append(np.asarray(gather_solution_1d(xb, lay, n)))
        assert np.array_equal(outs[0], outs[1]), \
            "1D solve fori flavor diverged bitwise from unrolled"
        np.testing.assert_allclose(outs[0], np.asarray(x_ref),
                                   rtol=1e-9, atol=1e-12)

    def test_2d_2x4_gather_false_bitmatches(self):
        a, b = _fixture(48, 2)
        x_ref, _ = block_jordan_solve(a, b, block_size=8)
        res = solve_system(a, b, block_size=8, workers=(2, 4),
                           gather=False)
        # Auto ranks the probe-ahead flavor first since ISSUE 16.
        assert res.engine == "solve_lookahead"
        # gather=False still returns the dense X (it is O(n·k) and the
        # verification needs it) PLUS the sharded row blocks.
        assert np.array_equal(np.asarray(res.x), np.asarray(x_ref))
        assert res.x_blocks is not None and res.layout is not None
        from tpu_jordan.parallel.jordan2d_inplace import (
            gather_solution_2d)

        x2 = gather_solution_2d(res.x_blocks, res.layout, 48)
        assert np.array_equal(np.asarray(x2), np.asarray(res.x))

    @pytest.mark.slow   # heavy duplicate of the 2x4 leg (tier-1 keeps
    #   the smoke p=2 + 2x4 pins; the gathered 2D twin runs nightly)
    def test_2d_2x2_gathered_bitmatches(self):
        a, b = _fixture(64, 3)
        x_ref, _ = block_jordan_solve(a, b, block_size=8)
        res = solve_system(a, b, block_size=8, workers=(2, 2))
        assert np.array_equal(np.asarray(res.x), np.asarray(x_ref))
        assert res.x_blocks is None

    @pytest.mark.slow  # tier-1 budget: nightly keeps the FLOPs pin; parity siblings stay fast
    def test_per_device_flops_strictly_below_single_device(self):
        # The acceptance FLOP pin: the sharded executable's OWN
        # cost_analysis (the per-device SPMD program) must land
        # strictly below the single-device solve's at the same n.
        import jax

        from tpu_jordan.obs import hwcost as _hwcost
        from tpu_jordan.parallel import make_mesh
        from tpu_jordan.parallel.layout import CyclicLayout
        from tpu_jordan.parallel.ring_gemm import (
            _to_identity_padded_blocks)
        from tpu_jordan.parallel.sharded_inplace import (
            compile_sharded_jordan_solve, scatter_rhs_1d)

        n, m, k, p = 128, 16, 4, 4
        a, b = _fixture(n, k, jnp.float32)
        single = jax.jit(
            lambda aa, bb: block_jordan_solve(aa, bb, block_size=m)
        ).lower(a, b).compile()
        fs = _hwcost.executable_cost(single).flops
        mesh = make_mesh(p)
        lay = CyclicLayout.create(n, m, p)
        W = _to_identity_padded_blocks(a, lay, mesh)
        X = scatter_rhs_1d(b, lay, mesh)
        run = compile_sharded_jordan_solve(W, X, mesh, lay)
        fd = _hwcost.executable_cost(run).flops
        assert fs and fd, "cost_analysis unavailable on this backend"
        assert fd < fs, (
            f"per-device flops {fd} not below single-device {fs}")
        # ~1/p up to the unsharded probe/glue share: well under 1/2
        # at p=4.
        assert fd / fs < 0.5


class TestSolveForiEngine:
    def test_bitmatches_unrolled(self):
        # n=32 (Nr=4) keeps six fresh traces affordable in tier-1.
        for gen, n, m, k, dt, spd in [
            ("rand", 32, 8, 3, jnp.float64, False),
            ("kms", 32, 8, 2, jnp.float64, True),
            ("crand", 32, 8, 2, jnp.complex64, False),
        ]:
            a, b = _fixture(n, k, dt, gen)
            xu, su = block_jordan_solve(a, b, block_size=m, spd=spd)
            xf, sf = block_jordan_solve_fori(a, b, block_size=m,
                                             spd=spd)
            assert bool(su) == bool(sf) is False
            assert np.array_equal(np.asarray(xu), np.asarray(xf)), \
                f"fori diverged bitwise ({gen}, spd={spd})"

    def test_unroll_cap_is_typed_and_names_the_remedy(self):
        # ISSUE 15 satellite: the old ValueError became a typed
        # UsageError that names the fori engine as the remedy.
        n, m = 520, 8          # Nr = 65 > MAX_UNROLL_NR = 64
        a, b = _fixture(n, 1, jnp.float32)
        with pytest.raises(UsageError, match="solve_fori"):
            block_jordan_solve(a, b, block_size=m)

    def test_auto_resolves_large_nr_to_fori(self):
        # engine="auto" beyond MAX_UNROLL_NR lands on the fori engine
        # (solve_aug is illegal there) and the solve still gates clean.
        n, m = 520, 8
        a, b = _fixture(n, 2, jnp.float32)
        res = solve_system(a, b, block_size=m)
        assert res.engine == "solve_fori"
        assert res.rel_residual < 1e-5

    def test_fori_trace_refusal_is_typed(self):
        n, m = 520, 8
        a, b = _fixture(n, 1, jnp.float32)
        with pytest.raises(UsageError, match="numerics='trace'"):
            solve_system(a, b, block_size=m, numerics="trace")


class TestDistributedSolveFlagContract:
    def test_numerics_trace_distributed_typed_refusal(self):
        a, b = _fixture(32, 1)
        with pytest.raises(UsageError, match="summary"):
            solve_system(a, b, block_size=8, workers=2,
                         numerics="trace")

    def test_spd_distributed_typed_refusal(self):
        a, b = _fixture(32, 1)
        with pytest.raises(UsageError, match="spd"):
            solve_system(a, b, block_size=8, workers=2, assume="spd")

    def test_complex_distributed_typed_refusal(self):
        a, b = _fixture(32, 1, jnp.complex64, "crand")
        with pytest.raises(UsageError, match="complex"):
            solve_system(a, b, block_size=8, workers=2)

    def test_solve_sharded_requires_a_mesh(self):
        a, b = _fixture(32, 1)
        with pytest.raises(UsageError, match="workers"):
            solve_system(a, b, block_size=8, engine="solve_sharded")

    def test_single_device_engine_refused_on_mesh(self):
        a, b = _fixture(32, 1)
        with pytest.raises(UsageError, match="solve_sharded"):
            solve_system(a, b, block_size=8, workers=2,
                         engine="solve_aug")

    def test_gather_false_single_device_typed(self):
        a, b = _fixture(32, 1)
        with pytest.raises(UsageError, match="gather"):
            solve_system(a, b, block_size=8, gather=False)

    def test_numerics_summary_distributed_ok(self):
        a, b = _fixture(32, 2)
        res = solve_system(a, b, block_size=8, workers=2,
                           numerics="summary")
        assert res.numerics is not None
        assert res.numerics.workload == "solve"


class TestDistributedSolvePolicy:
    def test_refine_rung_reuses_the_sharded_executable(self):
        # A policy on the distributed path: the gate judges the dense
        # verification; a clean solve climbs zero rungs.
        from tpu_jordan.resilience import ResiliencePolicy

        a, b = _fixture(48, 2)
        res = solve_system(a, b, block_size=8, workers=2,
                           policy=ResiliencePolicy())
        assert res.recovery == ()
        assert res.rel_residual < 1e-12

    @pytest.mark.slow  # tier-1 budget: the refusal/policy siblings stay fast
    def test_recovered_x_blocks_are_rescattered(self):
        # Review-hardening pin: a recovery rung replaces x — the
        # gather=False blocks must be RE-SCATTERED from the recovered
        # solution, never the stale gate-failing one.  An fp32 gate
        # SLO on a bf16-storage solve forces the refine rung (which
        # re-runs the SAME sharded executable on the residual RHS).
        from tpu_jordan.parallel.sharded_inplace import (
            gather_solution_1d)
        from tpu_jordan.resilience import ResiliencePolicy

        a, b = _fixture(48, 2, jnp.bfloat16)
        res = solve_system(a, b, block_size=8, workers=2, gather=False,
                           policy=ResiliencePolicy(
                               gate_dtype=jnp.float32))
        assert [r["rung"] for r in res.recovery] == ["refine"]
        x2 = gather_solution_1d(res.x_blocks, res.layout, 48)
        assert np.array_equal(np.asarray(x2), np.asarray(res.x))
        assert res.rel_residual < 1e-5


class TestLookaheadSolve:
    """The probe-ahead distributed solve (ISSUE 16): the [A | B]
    elimination with step t+1's condition probe issued right after the
    critical panel.  X bits, pivot sequence, and the collective
    multiset (tests/test_comm.py) pin identical to
    engine='solve_sharded'."""

    @pytest.mark.slow       # tier-1 keeps test_1d_p2_bitmatches (auto
    def test_1d_forced_swaps_and_ragged_bitmatch(self):  # → lookahead)
        # absdiff (a swap every superstep, exact ties) at ragged n: the
        # carried decision must reproduce the in-loop probe choices
        # through the identity-padded tail.
        a, b = _fixture(45, 2, gen="absdiff")
        base = solve_system(a, b, block_size=8, workers=4,
                            engine="solve_sharded")
        la = solve_system(a, b, block_size=8, workers=4,
                          engine="solve_lookahead")
        assert la.engine == "solve_lookahead"
        assert np.array_equal(np.asarray(la.x), np.asarray(base.x)), \
            "probe-ahead 1D solve diverged bitwise from solve_sharded"

    @pytest.mark.slow       # tier-1: test_2d_2x4_gather_false pins it
    def test_2d_gather_false_bitmatch(self):
        a, b = _fixture(48, 3)
        base = solve_system(a, b, block_size=8, workers=(2, 2),
                            gather=False, engine="solve_sharded")
        la = solve_system(a, b, block_size=8, workers=(2, 2),
                          gather=False, engine="solve_lookahead")
        assert np.array_equal(np.asarray(la.x), np.asarray(base.x))
        assert np.array_equal(np.asarray(jnp.asarray(la.x_blocks)),
                              np.asarray(jnp.asarray(base.x_blocks)))

    def test_spd_refusal_is_typed_and_names_legal_engines(self):
        # The SPD path is pivot-free: there is no condition probe to
        # move ahead — refusing beats silently running a probe-ful
        # engine under the requested label.
        a, b = _fixture(48, 2)
        with pytest.raises(UsageError, match="nothing to probe ahead"):
            solve_system(a, b, block_size=8, assume="spd",
                         engine="solve_lookahead")

    def test_single_device_refusal_is_typed(self):
        # Not wired on the single-device augmented engine: the refusal
        # names the distributed spelling.
        a, b = _fixture(48, 2)
        with pytest.raises(UsageError, match="workers"):
            solve_system(a, b, block_size=8, engine="solve_lookahead")

    def test_unroll_cap_refusal_is_typed(self):
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n = 8 * (MAX_UNROLL_NR + 4)
        a, b = _fixture(n, 1, dtype=jnp.float32)
        with pytest.raises(UsageError, match="unrolled-only"):
            solve_system(a, b, block_size=8, workers=4,
                         engine="solve_lookahead")
