"""Fused normalize-and-eliminate kernel (ops/pallas_update.py) and the
grouped_pallas engine plumbing (ISSUE 6).

Interpret-mode parity on CPU, same policy as test_pallas_probe.py: the
kernel is the production group-closing superstep on TPU; these tests pin
its semantics — bitwise against the XLA grouped engine's own matmul
sequence at fp32 — so a Mosaic/tiling regression can't silently change
results on hardware.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from tpu_jordan.ops import pallas_update as pu
from tpu_jordan.ops.pallas_update import (
    fused_normalize_eliminate,
    measured_phase_fractions,
)

HI = lax.Precision.HIGHEST


def _operands(rng, Nr, m, k, j, t):
    """Random operands honoring the engine's caller contract: U pivot
    rows zeroed, P's closing slot (row-block j) zero, P's pivot-column
    block of earlier rows zeroed."""
    N, KM = Nr * m, k * m
    V = jnp.asarray(rng.standard_normal((N, N)), jnp.float32)
    U = np.asarray(rng.standard_normal((N, KM)), np.float32)
    U[t * m:(t + 1) * m] = 0.0
    P = np.asarray(rng.standard_normal((KM, N)), np.float32)
    P[j * m:(j + 1) * m] = 0.0
    P[:j * m, t * m:(t + 1) * m] = 0.0
    H = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)
    rows_p = jnp.asarray(rng.standard_normal((m, N)), jnp.float32)
    return V, jnp.asarray(U), jnp.asarray(P), H, rows_p


def _reference_update(V, U, P, H, rows_p, t, j, m):
    """The XLA grouped engine's group-closing sequence, verbatim
    (ops/jordan_inplace.py): normalize, insert H, zero the pivot
    column, write the pivot rows, record P, subtract U·P."""
    prow = jnp.matmul(H, rows_p, precision=HI)
    prow = prow.at[:, t * m:(t + 1) * m].set(H)
    V = V.at[:, t * m:(t + 1) * m].set(0.0)
    V = V.at[t * m:(t + 1) * m, :].set(prow)
    P = P.at[j * m:(j + 1) * m, :].set(prow)
    return V - jnp.matmul(U, P, precision=HI)


class TestFusedKernelParity:
    @pytest.mark.parametrize("Nr,m,k,j,t", [
        (4, 16, 2, 1, 1),            # mid-matrix pivot
        (4, 16, 2, 1, 3),            # last block row
        (4, 16, 2, 0, 0),            # j=0: P has no earlier rows
        (6, 16, 4, 3, 3),            # wider group
        # tier-1 headroom (the 870 s rule): two geometry variants run
        # nightly; the four above cover j=0/closing, tail-tile pivots,
        # and the wider group.
        pytest.param(6, 16, 2, 1, 5,
                     marks=pytest.mark.slow),   # pivot in final tile
        pytest.param(2, 8, 2, 1, 1,
                     marks=pytest.mark.slow),   # tiny blocks
    ])
    def test_bitwise_matches_xla_sequence(self, rng, Nr, m, k, j, t):
        V, U, P, H, rows_p = _operands(rng, Nr, m, k, j, t)
        ref = _reference_update(V, U, P, H, rows_p, t, j, m)
        out = fused_normalize_eliminate(V, U, P, H, rows_p, t=t, j=j,
                                        m=m, interpret=True)
        assert bool(jnp.all(out == ref)), "fused kernel diverged bitwise"

    def test_tiled_grid_bitwise(self, rng, monkeypatch):
        # Shrink the VMEM budget so the launch genuinely tiles (several
        # programs per axis) and the tiling must not change a single
        # bit — the full-contraction-per-element design.
        Nr, m, k, j, t = 6, 8, 2, 1, 2
        V, U, P, H, rows_p = _operands(rng, Nr, m, k, j, t)
        ref = fused_normalize_eliminate(V, U, P, H, rows_p, t=t, j=j,
                                        m=m, interpret=True)
        monkeypatch.setattr(pu, "_UPD_BUDGET", pu._tile_bytes(8, 8, 16, 8))
        jax.clear_caches()
        try:
            assert pu._update_tiles(Nr * m, k * m, m) == (m, m)
            out = fused_normalize_eliminate(V, U, P, H, rows_p, t=t,
                                            j=j, m=m, interpret=True)
            assert bool(jnp.all(out == ref))
        finally:
            jax.clear_caches()

    def test_update_tiles_properties(self):
        for N, KM, m in [(512, 256, 128), (2048, 256, 128),
                         (768, 512, 256), (96, 32, 16), (64, 16, 8)]:
            R, C = pu._update_tiles(N, KM, m)
            assert R == C and R % m == 0 and N % R == 0
            assert (pu._tile_bytes(R, C, KM, m) <= pu._UPD_BUDGET
                    or R == m)
            assert R <= pu._MAX_TILE

    def test_bf16_mode_rounds_operands(self, rng):
        Nr, m, k, j, t = 4, 16, 2, 1, 1
        V, U, P, H, rows_p = _operands(rng, Nr, m, k, j, t)
        f32 = fused_normalize_eliminate(V, U, P, H, rows_p, t=t, j=j,
                                        m=m, interpret=True)
        b16 = fused_normalize_eliminate(V, U, P, H, rows_p, t=t, j=j,
                                        m=m, mode="bf16", interpret=True)
        assert b16.dtype == jnp.float32          # fp32 accumulate/storage
        assert not bool(jnp.all(f32 == b16))     # operands were rounded
        # bf16-grade agreement: relative to the update's magnitude.
        scale = float(jnp.max(jnp.abs(f32)))
        assert float(jnp.max(jnp.abs(f32 - b16))) < 0.05 * scale
        # The pivot rows carry the fp32-accumulated normalized row in
        # BOTH modes' storage; the H insertion is exact in both.
        np.testing.assert_allclose(
            np.asarray(b16[t * m:(t + 1) * m, t * m:(t + 1) * m]),
            np.asarray(H), rtol=0, atol=0)

    def test_unknown_mode_rejected(self, rng):
        V, U, P, H, rows_p = _operands(rng, 2, 8, 2, 1, 0)
        with pytest.raises(ValueError, match="precision mode"):
            fused_normalize_eliminate(V, U, P, H, rows_p, t=0, j=1,
                                      m=8, mode="fp64", interpret=True)


class TestMeasuredPhaseFractions:
    def test_fractions_partition_and_cache(self):
        pu._PHASE_FRACTIONS_CACHE.clear()
        fr = measured_phase_fractions(64, 16, 2, interpret=True)
        assert set(fr) == {"pivot", "permute", "eliminate"}
        assert abs(sum(fr.values()) - 1.0) < 1e-9
        assert all(v > 0 for v in fr.values())
        # Second call is a cache hit: the same dict object, no launches.
        assert measured_phase_fractions(64, 16, 2, interpret=True) is fr

    def test_capped_bracket_twin(self, monkeypatch):
        # Beyond _BRACKET_MAX_N the brackets run on a size-capped twin
        # (same m/group) with per-phase work-ratio scaling — the OOM
        # guard for telemetry'd 16384-class solves.  Force the cap low
        # so the scaling path runs at test sizes.
        monkeypatch.setattr(pu, "_BRACKET_MAX_N", 32)
        pu._PHASE_FRACTIONS_CACHE.clear()
        try:
            fr = measured_phase_fractions(128, 8, 2, interpret=True)
            assert abs(sum(fr.values()) - 1.0) < 1e-9
            assert all(v > 0 for v in fr.values())
        finally:
            pu._PHASE_FRACTIONS_CACHE.clear()
            jax.clear_caches()


class TestDriverPlumbing:
    def test_distributed_rejected(self):
        from tpu_jordan.driver import UsageError, solve

        with pytest.raises(UsageError, match="single-device"):
            solve(n=64, block_size=8, workers=4, engine="grouped_pallas")

    def test_solver_distributed_rejected(self):
        from tpu_jordan.driver import UsageError
        from tpu_jordan.models import JordanSolver

        with pytest.raises(UsageError, match="single-device"):
            JordanSolver(n=64, block_size=8, workers=4,
                         engine="grouped_pallas")

    def test_beyond_unroll_cap_rejected(self):
        from tpu_jordan.driver import UsageError, single_device_invert
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n = 8 * (MAX_UNROLL_NR + 4)
        with pytest.raises(UsageError, match="unrolled-only"):
            single_device_invert(n, 8, "grouped_pallas", 2)

    def test_float64_rejected(self, rng):
        from tpu_jordan.ops import block_jordan_invert_inplace_grouped_pallas

        a = jnp.asarray(rng.standard_normal((32, 32)), jnp.float64)
        with pytest.raises(ValueError, match="fp32"):
            block_jordan_invert_inplace_grouped_pallas(
                a, block_size=8, interpret=True)

    def test_resolve_engine_defaults_group2(self):
        from tpu_jordan.driver import resolve_engine

        assert resolve_engine("grouped_pallas", 0) == ("grouped_pallas", 2)
        assert resolve_engine("grouped_pallas", 4) == ("grouped_pallas", 4)
        assert resolve_engine("grouped_pallas_bf16", 0) == (
            "grouped_pallas_bf16", 2)

    def test_measured_phase_spans_on_trace(self):
        # The Pallas path's execute children are MEASURED (kernel
        # brackets), never modeled — the obs-layer tentpole contract,
        # enforced artifact-side by tools/check_telemetry.py.
        from tpu_jordan.driver import solve
        from tpu_jordan.obs.spans import PHASES, Telemetry

        tel = Telemetry()
        r = solve(n=64, block_size=16, engine="grouped_pallas",
                  telemetry=tel)
        ex = r.trace.find("execute")
        kids = {c.name: c.attrs for c in ex.children}
        assert set(kids) == set(PHASES)
        for attrs in kids.values():
            assert attrs.get("measured") is True
            assert attrs.get("source") == "kernel_bracket"
            assert "modeled" not in attrs
        # The children tile the execute span exactly.
        assert ex.children[0].t_start == ex.t_start
        assert ex.children[-1].t_end == ex.t_end
