"""ISSUE 10 tentpole part 2 — XLA cost/memory accounting.

Pins: ``executable_cost`` reads the compiler's own numbers (exact on a
known matmul); the (8/3)n³ analytical Gauss–Jordan count matches the
real executable's ``cost_analysis`` within tolerance at a pinned shape
(the ``invert_flops`` retirement parity test); execute spans carry the
achieved-vs-analytical attrs; the serve stats expose per-bucket
executable accounting; unavailable analysis stays absent — never
modeled; and the Prometheus exporter emits ``# HELP`` next to every
``# TYPE`` (checker-validated both ways).
"""

import importlib.util
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_jordan.obs import hwcost
from tpu_jordan.obs.metrics import REGISTRY
from tpu_jordan.obs.spans import Span, Telemetry

_tool = (pathlib.Path(__file__).resolve().parent.parent / "tools"
         / "check_telemetry.py")
_spec = importlib.util.spec_from_file_location("check_telemetry", _tool)
check_telemetry = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_telemetry)


class TestExecutableCost:
    def test_exact_on_known_matmul(self):
        """XLA counts a (64,64)x(64,64) matmul as exactly 2*64^3
        flops — the ground truth the reader must reproduce."""
        f = jax.jit(lambda a, b: a @ b).lower(
            jnp.zeros((64, 64), jnp.float32),
            jnp.zeros((64, 64), jnp.float32)).compile()
        cost = hwcost.executable_cost(f)
        assert cost.available
        assert cost.flops == 2.0 * 64**3
        assert cost.bytes_accessed and cost.bytes_accessed > 0
        assert cost.argument_bytes == 2 * 64 * 64 * 4
        assert cost.output_bytes == 64 * 64 * 4
        assert cost.hbm_bytes >= cost.argument_bytes
        assert cost.arithmetic_intensity > 0
        assert cost.to_json()["source"] == "xla_cost_analysis"

    def test_gauss_jordan_parity_at_pinned_shape(self):
        """The invert_flops retirement pin (ISSUE 10 satellite): the
        (8/3)n³ analytical count of the blocked in-place Gauss–Jordan
        — trailing 2n³ sweep + probe block inverses + normalize
        side-products — matches the REAL executable's cost_analysis
        within 15% at the pinned (n=256, m=64) shape.  Measured ratio
        this session: 0.967."""
        from tpu_jordan.ops import block_jordan_invert_inplace, generate

        a = generate("absdiff", (256, 256), jnp.float32)
        c = jax.jit(lambda x: block_jordan_invert_inplace(
            x, block_size=64)).lower(a).compile()
        cost = hwcost.executable_cost(c)
        assert cost.available and cost.flops
        ratio = cost.flops / hwcost.gauss_jordan_flops(256)
        assert abs(ratio - 1.0) < 0.15, (
            f"cost_analysis {cost.flops:.4g} vs (8/3)n^3 "
            f"{hwcost.gauss_jordan_flops(256):.4g} (ratio {ratio:.3f})")

    def test_invert_flops_shim_delegates(self):
        from tpu_jordan.utils.profiling import invert_flops

        assert invert_flops(512) == hwcost.baseline_invert_flops(512)
        assert invert_flops(512) == 2.0 * 512**3

    def test_unavailable_is_absent_not_modeled(self):
        cost = hwcost.executable_cost(object())
        assert cost is hwcost.UNAVAILABLE
        assert not cost.available
        assert cost.flops is None and cost.hbm_bytes is None
        sp = Span("execute", 0.0, 1.0)
        hwcost.attach_execute_cost(sp, cost, analytical_flops=1e9)
        assert "xla_flops" not in sp.attrs
        assert "achieved_tflops_analytical" not in sp.attrs

    def test_attach_execute_cost_attrs(self):
        cost = hwcost.ExecutableCost(available=True, flops=2e12,
                                     bytes_accessed=1e9)
        sp = Span("execute", 0.0, 2.0)
        hwcost.attach_execute_cost(sp, cost, analytical_flops=1e12)
        assert sp.attrs["xla_flops"] == 2e12
        assert sp.attrs["achieved_tflops_xla"] == 1.0
        assert sp.attrs["achieved_tflops_analytical"] == 0.5
        assert sp.attrs["xla_vs_analytical"] == 2.0
        assert sp.attrs["arithmetic_intensity"] == 2000.0


class TestWiring:
    def test_solve_execute_span_carries_cost(self):
        from tpu_jordan.driver import solve

        tel = Telemetry()
        solve(48, 16, generator="rand", engine="inplace",
              telemetry=tel)
        esp = tel.find("execute")
        assert esp.attrs["xla_flops"] > 0
        assert esp.attrs["achieved_tflops_xla"] > 0
        assert esp.attrs["achieved_tflops_analytical"] > 0
        assert esp.attrs["arithmetic_intensity"] > 0
        # The real executable does MORE work than the hand 2n³ count
        # (probe + residual-free path still > 1 at small n).
        assert esp.attrs["xla_vs_analytical"] > 1.0

    def test_solver_model_cost_and_span(self):
        from tpu_jordan.models import JordanSolver

        tel = Telemetry()
        sol = JordanSolver(n=32, block_size=8, engine="inplace",
                           telemetry=tel)
        inv, sing = sol.invert(np.eye(32) * 2.0)
        assert not bool(sing)
        assert sol.cost is not None and sol.cost.available
        esp = tel.find("execute")
        assert esp.attrs["xla_flops"] == sol.cost.flops

    def test_serve_stats_executable_block_and_gauges(self):
        from tpu_jordan.serve.stats import ServeStats

        cost = hwcost.ExecutableCost(available=True, flops=3e9,
                                     bytes_accessed=1e8,
                                     argument_bytes=100, output_bytes=50,
                                     temp_bytes=25)
        st = ServeStats(labels={"replica": "7"})
        st.executable_cost(64, cost)
        snap = st.snapshot()
        exe = snap["buckets"]["64"]["executable"]
        assert exe["flops"] == 3e9 and exe["hbm_bytes"] == 175
        g = REGISTRY.gauge("tpu_jordan_executable_flops")
        assert g.value(bucket=64, replica="7") == 3e9
        assert REGISTRY.gauge("tpu_jordan_executable_hbm_bytes").value(
            bucket=64, replica="7") == 175
        # Unavailable records nothing — absent, never zeroed.
        st.executable_cost(128, hwcost.UNAVAILABLE)
        assert "executable" not in st.snapshot()["buckets"].get(
            "128", {})

    def test_device_memory_absent_on_cpu(self):
        """The CPU backend reports no allocator stats: the watermark
        gauges stay absent (honest) and the sampler returns None."""
        assert hwcost.device_memory_stats() is None
        assert hwcost.observe_device_memory() is None

    def test_runtime_env_fingerprint(self):
        env = hwcost.runtime_env()
        assert env["jax"] and env["jaxlib"]
        assert env["backend"] == "cpu"
        assert env["device_count"] == 8
        assert env["host_cpu_count"] >= 1


class TestPrometheusHelp:
    def test_every_type_has_help_both_ways(self):
        from tpu_jordan.obs.export import to_prometheus

        text = to_prometheus()
        helped = {line.split(None, 3)[2]
                  for line in text.splitlines()
                  if line.startswith("# HELP ")}
        typed = {line.split()[2] for line in text.splitlines()
                 if line.startswith("# TYPE ")}
        assert typed and typed == helped
        # The checker agrees (accept)...
        assert check_telemetry.check_prometheus(text, "registry") > 0
        # ...and rejects a doctored scrape missing HELP lines (reject).
        doctored = "\n".join(line for line in text.splitlines()
                             if not line.startswith("# HELP"))
        with pytest.raises(AssertionError, match="no # HELP"):
            check_telemetry.check_prometheus(doctored, "doctored")

    def test_orphaned_help_rejected(self):
        with pytest.raises(AssertionError, match="no # TYPE"):
            check_telemetry.check_prometheus(
                "# HELP tpu_jordan_ghost gone\n"
                "# TYPE tpu_jordan_real counter\n"
                "# HELP tpu_jordan_real fine\n"
                "tpu_jordan_real 1\n", "orphan")

    def test_unregistered_help_falls_back_visibly(self):
        from tpu_jordan.obs.export import to_prometheus
        from tpu_jordan.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("tpu_jordan_undocumented").inc()
        text = to_prometheus(reg)
        assert ("# HELP tpu_jordan_undocumented (no help registered)"
                in text)
        assert check_telemetry.check_prometheus(text, "fallback") == 1
