"""ISSUE 19 — the work observatory.

The reconciliation invariant is the heart: for every distributed
engine configuration, the per-(worker, superstep, phase) analytical
FLOP inventory (``obs/work.engine_report`` — cyclic ownership ×
live-column window × workload) must sum EXACTLY to the engine's
headline convention (invert ``2n³``, solve ``n³ + n²k`` — integer
arithmetic, no tolerance), with the ragged tail's reduced-height last
block threaded through every share (satellite 3: non-block-aligned n
on 1D and 2D meshes).  Plus: the driver/linalg/solver integration
(``SolveResult.work`` / ``SolveSystemResult.work`` /
``JordanSolver.work``, execute-span attrs, the ``tpu_jordan_work_*``
gauges), the hwcost pin (devices × cost_analysis vs the traced model),
the measured fleet-skew layer (ServeStats cross-replica rollup →
``FleetSkewJudge`` → transition-only recorder events → the autoscaler
veto), and the ``tools/check_work.py`` both-ways gate.
"""

import importlib.util
import json
import pathlib
from types import SimpleNamespace

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.obs import work
from tpu_jordan.obs.metrics import REGISTRY
from tpu_jordan.obs.recorder import RECORDER
from tpu_jordan.parallel.layout import (
    CyclicLayout,
    CyclicLayout2D,
    last_block_height,
    num_block_rows,
)

_repo = pathlib.Path(__file__).resolve().parent.parent
_spec = importlib.util.spec_from_file_location(
    "check_work", _repo / "tools" / "check_work.py")
check_work = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_work)


# ---------------------------------------------------------------------
# Analytical inventories: pure host-side layout math.
# ---------------------------------------------------------------------


class TestInventoryExactness:
    """Satellite 3: the ragged-tail edge (``last_block_height`` /
    ``padded_num_blocks``) through the work inventories at
    non-block-aligned n — shares summing exactly to the convention
    total, on 1D and 2D meshes, both workloads."""

    @pytest.mark.parametrize("n,m,p", [(44, 8, 4), (7, 3, 2),
                                       (26, 8, 4), (100, 16, 8),
                                       (64, 8, 4)])
    def test_1d_invert_exact(self, n, m, p):
        rep = work.engine_report(engine="inplace",
                                 lay=CyclicLayout.create(n, m, p))
        assert rep.exact
        assert rep.accounted_flops() == 2 * n ** 3
        assert sum(rep.per_superstep) == 2 * n ** 3
        assert len(rep.per_worker) == p
        assert rep.supersteps == num_block_rows(n, m)
        assert rep.last_height == last_block_height(n, m)
        assert abs(sum(rep.shares().values()) - 1.0) < 1e-12

    @pytest.mark.parametrize("n,m,p,k", [(44, 8, 4, 3), (26, 8, 4, 1),
                                         (37, 8, 2, 5)])
    def test_1d_solve_exact(self, n, m, p, k):
        rep = work.engine_report(engine="solve_sharded",
                                 lay=CyclicLayout.create(n, m, p), k=k)
        assert rep.workload == "solve"
        assert rep.exact
        assert rep.accounted_flops() == n ** 3 + n ** 2 * k
        assert sum(rep.per_superstep) == n ** 3 + n ** 2 * k

    @pytest.mark.parametrize("n,m,pr,pc", [(44, 8, 2, 2), (60, 8, 2, 4),
                                           (37, 8, 4, 2),
                                           (100, 16, 2, 4)])
    def test_2d_invert_exact(self, n, m, pr, pc):
        rep = work.engine_report(
            engine="inplace", lay=CyclicLayout2D.create(n, m, pr, pc))
        assert rep.exact
        assert rep.accounted_flops() == 2 * n ** 3
        assert len(rep.per_worker) == pr * pc
        assert set(rep.per_worker) == {f"{r},{c}" for r in range(pr)
                                       for c in range(pc)}

    @pytest.mark.parametrize("n,m,pr,pc,k", [(44, 8, 2, 2, 3),
                                             (60, 8, 2, 4, 7),
                                             (37, 8, 4, 2, 1)])
    def test_2d_solve_exact_with_cyclic_k_split(self, n, m, pr, pc, k):
        """The k RHS columns split cyclically over the column workers —
        including k not divisible by pc — and the total stays an exact
        integer identity."""
        rep = work.engine_report(
            engine="solve_sharded",
            lay=CyclicLayout2D.create(n, m, pr, pc), k=k)
        assert rep.exact
        assert rep.accounted_flops() == n ** 3 + n ** 2 * k

    def test_ragged_tail_changes_shares_aligned_does_not(self):
        """The reduced-height tail block gives its cyclic owner less
        work: ragged n skews the shares, block-aligned p | Nr n pins
        skew to exactly 1 and the penalty to exactly 0."""
        ragged = work.engine_report(engine="inplace",
                                    lay=CyclicLayout.create(44, 8, 4))
        assert ragged.last_height == 4
        assert ragged.skew() > 1.0
        assert ragged.ragged_penalty > 0.0
        aligned = work.engine_report(engine="inplace",
                                     lay=CyclicLayout.create(64, 8, 4))
        assert aligned.last_height == 8
        assert aligned.skew() == 1.0
        assert aligned.ragged_penalty == 0.0

    def test_phase_split_pivot_only_on_owner(self):
        """The pivot phase belongs to the superstep's owning row
        worker; everyone eliminates.  Total pivot work is
        Σ f_t · h_t — strictly positive and strictly smaller than the
        eliminate bulk on any p > 1 mesh."""
        rep = work.engine_report(engine="inplace",
                                 lay=CyclicLayout.create(26, 8, 4))
        piv = sum(d["pivot"] for d in rep.per_worker.values())
        elim = sum(d["eliminate"] for d in rep.per_worker.values())
        assert piv > 0 and elim > piv
        assert piv + elim == rep.convention

    def test_unknown_engine_refused(self):
        with pytest.raises(ValueError, match="work inventory"):
            work.engine_report(engine="mystery",
                               lay=CyclicLayout.create(26, 8, 4))

    def test_unknown_workload_refused(self):
        with pytest.raises(ValueError, match="convention"):
            work.convention_flops(8, "lstsq")


class TestExecutedModel:
    def test_augmented_strip_doubles_invert_width(self):
        base = work.executed_model_flops("inplace", "invert", N=64, m=8)
        aug = work.executed_model_flops("augmented", "invert", N=64,
                                        m=8)
        assert aug == 2 * base == 4.0 * 64 ** 3

    def test_solve_unrolled_shrinks_fori_does_not(self):
        fori = work.executed_model_flops("solve_sharded", "solve",
                                         N=64, m=8, k=2, unroll=False)
        unrolled = work.executed_model_flops("solve_sharded", "solve",
                                             N=64, m=8, k=2,
                                             unroll=True)
        assert fori == 2.0 * 64 * 64 * (64 + 2)
        assert unrolled < fori

    def test_xla_pin_fori_judges_traced_body_once(self):
        """cost_analysis is a STATIC HLO count — a fori body is counted
        once, never × trip count — so the fori flavors judge devices ×
        per-device against executed/Nr."""
        rep = work.engine_report(engine="swapfree",
                                 lay=CyclicLayout.create(64, 8, 4))
        assert rep.unroll is False
        traced = rep.executed_model / rep.padded_supersteps
        cost = SimpleNamespace(available=True,
                               flops=2.0 * traced / rep.n_devices)
        x = rep.attach_xla(cost)
        assert x["available"] and x["within"]
        assert x["xla_vs_model"] == pytest.approx(2.0, rel=1e-3)
        assert x["model_traced_flops"] == pytest.approx(traced)

    def test_xla_pin_honest_when_cost_unavailable(self):
        rep = work.engine_report(engine="inplace",
                                 lay=CyclicLayout.create(26, 8, 4))
        assert rep.attach_xla(None) == {"available": False}
        assert rep.attach_xla(
            SimpleNamespace(available=False, flops=None)) == {
                "available": False}

    def test_xla_pin_flags_out_of_band(self):
        rep = work.engine_report(engine="inplace",
                                 lay=CyclicLayout.create(26, 8, 4))
        cost = SimpleNamespace(
            available=True,
            flops=100.0 * rep.executed_model / rep.n_devices)
        assert rep.attach_xla(cost)["within"] is False


# ---------------------------------------------------------------------
# Export: metrics, span attrs, snapshot.
# ---------------------------------------------------------------------


class TestExport:
    def test_metrics_and_span_attrs(self):
        rep = work.engine_report(engine="inplace",
                                 lay=CyclicLayout.create(44, 8, 4))
        rep.observe_metrics()
        snap = REGISTRY.snapshot()
        skew_series = snap["tpu_jordan_work_skew"]["series"]
        got = {tuple(sorted(s["labels"].items())): s["value"]
               for s in skew_series}
        assert got[(("engine", "inplace"),)] == pytest.approx(
            rep.skew())
        shares = snap["tpu_jordan_work_share"]["series"]
        mine = [s for s in shares
                if s["labels"].get("engine") == "inplace"]
        assert len(mine) >= 4
        span = SimpleNamespace(attrs={})
        rep.attach_span(span)
        assert span.attrs["work_skew"] == pytest.approx(rep.skew(),
                                                        rel=1e-3)
        assert span.attrs["work_ragged_penalty"] > 0

    def test_snapshot_carries_last_report(self):
        rep = work.engine_report(engine="inplace",
                                 lay=CyclicLayout.create(44, 8, 4))
        work.set_last_report(rep)
        snap = work.snapshot()
        assert snap["metric"] == "work_report"
        assert snap["last_solve"]["engine"] == "inplace"
        assert snap["last_solve"]["totals"]["exact"] is True


# ---------------------------------------------------------------------
# Layer two: measured fleet skew.
# ---------------------------------------------------------------------


class TestServeStatsSpread:
    def test_snapshot_has_labels_and_exec_ms(self):
        from tpu_jordan.serve.stats import ServeStats

        st = ServeStats(labels={"replica": "7"})
        st.batch("64", occupancy=1, exec_seconds=0.010,
                 queue_seconds=())
        snap = st.snapshot()
        assert snap["labels"] == {"replica": "7"}
        assert snap["exec_ms"]["p99"] == pytest.approx(10.0)

    def test_cross_replica_spread(self):
        from tpu_jordan.serve.stats import (ServeStats,
                                            cross_replica_spread)

        snaps = []
        for slot, base in (("0", 0.010), ("1", 0.030)):
            st = ServeStats(labels={"replica": slot})
            for _ in range(4):
                st.batch("64", occupancy=1, exec_seconds=base,
                         queue_seconds=())
            snaps.append(st.snapshot())
        sp = cross_replica_spread(snaps)
        assert sp["judged"] is True
        assert sp["p99_spread"] == pytest.approx(3.0)
        assert sp["max_replica"] == "1" and sp["min_replica"] == "0"

    def test_single_replica_not_judged(self):
        from tpu_jordan.serve.stats import (ServeStats,
                                            cross_replica_spread)

        st = ServeStats(labels={"replica": "0"})
        st.batch("64", occupancy=1, exec_seconds=0.01,
                 queue_seconds=())
        assert cross_replica_spread([st.snapshot()])["judged"] is False


class TestFleetSkewJudge:
    def test_straggler_lifecycle_transition_only(self):
        """Suspect → still-suspected (no duplicate event) → cleared:
        exactly one straggler_suspected and one straggler_cleared
        recorder event, and the counter moves once."""
        mark = RECORDER.total
        c = REGISTRY.counter("tpu_jordan_straggler_suspected_total")
        before = c.value(replica="2")
        judge = work.FleetSkewJudge()
        v = judge.assess({"0": 10.0, "1": 10.0, "2": 55.0})
        assert v["judged"] and v["suspected"] and v["replica"] == "2"
        assert judge.veto() is not None
        judge.assess({"0": 10.0, "1": 10.0, "2": 60.0})  # still sick
        v3 = judge.assess({"0": 10.0, "1": 10.0, "2": 11.0})
        assert not v3["suspected"]
        assert judge.veto() is None
        kinds = [e["kind"] for e in RECORDER.since(mark)
                 if e["kind"].startswith("straggler")]
        assert kinds == ["straggler_suspected", "straggler_cleared"]
        assert c.value(replica="2") == before + 1

    def test_layout_attributed_spread_stays_clean(self):
        """A replica slower exactly in proportion to its analytical
        critical path (a smaller mesh) must NOT be suspected — the
        'was it the layout or the replica?' disambiguation."""
        big = work.engine_report(engine="inplace",
                                 lay=CyclicLayout.create(44, 8, 8))
        small = work.engine_report(engine="inplace",
                                   lay=CyclicLayout.create(44, 8, 2))
        expected = {"0": work.expected_latency_factor(big),
                    "1": work.expected_latency_factor(small)}
        ratio = expected["1"] / expected["0"]
        assert ratio > work.STRAGGLER_SPREAD   # raw spread WOULD page
        v = work.FleetSkewJudge().assess(
            {"0": 10.0, "1": 10.0 * ratio}, expected=expected)
        assert v["judged"] is True
        assert v["spread"] == pytest.approx(1.0)
        assert v["suspected"] is False

    def test_single_replica_honestly_unjudged(self):
        v = work.FleetSkewJudge().assess({"0": 10.0})
        assert v["judged"] is False and v["suspected"] is False
        v2 = work.FleetSkewJudge().assess({"0": 10.0, "1": None,
                                           "2": 0.0})
        assert v2["judged"] is False


# ---------------------------------------------------------------------
# Driver / linalg / solver integration (real sharded executables).
# ---------------------------------------------------------------------


class TestIntegration:
    def test_driver_attaches_exact_report_with_xla(self):
        from tpu_jordan.driver import solve

        r = solve(28, 8, workers=4, engine="inplace")
        assert r.work is not None and r.work.exact
        assert r.work.engine == "inplace"
        assert len(r.work.per_worker) == 4
        assert r.work.ragged_penalty > 0          # 28 % 8 != 0
        assert r.work.xla["available"] and r.work.xla["within"]
        assert work.LAST_REPORT is r.work

    def test_solve_system_attaches_solve_report(self):
        from tpu_jordan.linalg import solve_system
        from tpu_jordan.ops import generate

        a = generate("absdiff", (28, 28), jnp.float32)
        b = generate("rand", (28, 2), jnp.float32, row_offset=28)
        r = solve_system(a, b, block_size=8, workers=2,
                         engine="solve_sharded")
        assert r.work is not None and r.work.exact
        assert r.work.workload == "solve" and r.work.rhs == 2
        assert r.work.accounted_flops() == 28 ** 3 + 28 ** 2 * 2

    @pytest.mark.slow  # tier-1 budget: the driver legs above pin the path
    def test_jordan_solver_warm_execute_keeps_work_accounting(self):
        """The warm-path pin with work accounting on: the report is
        built at compile, executes only set gauges/span attrs — no
        recompiles, no measurements."""
        from tpu_jordan.models import JordanSolver

        rng = np.random.default_rng(5)
        a = (2.0 * np.eye(36) + 0.1 * rng.standard_normal(
            (36, 36))).astype(np.float32)

        def counter(name):
            reg = REGISTRY.snapshot()
            return sum(s["value"] for s in
                       reg.get(name, {}).get("series", []))

        s = JordanSolver(36, block_size=8, workers=2, engine="inplace")
        s.invert(jnp.asarray(a))                   # compile + attach
        assert s.work is not None and s.work.exact
        assert s.work.xla is not None
        compiles = counter("tpu_jordan_compiles_total")
        s.invert(jnp.asarray(a))
        s.invert(jnp.asarray(a))
        assert counter("tpu_jordan_compiles_total") == compiles


# ---------------------------------------------------------------------
# The demo + checker, both ways.
# ---------------------------------------------------------------------


def _fake_cost(rep, factor=2.0):
    """A cost_analysis stand-in whose devices × per-device lands at
    ``factor`` × the traced model (in band for factor in [0.5, 4])."""
    model = rep.executed_model
    if not rep.unroll and rep.padded_supersteps:
        traced = (min(rep.group, rep.padded_supersteps)
                  if rep.group > 1 else 1)
        model = model * traced / rep.padded_supersteps
    return SimpleNamespace(available=True,
                           flops=factor * model / rep.n_devices)


@pytest.fixture(scope="module")
def demo_report():
    """A synthetic-but-honest work_demo report: the same leg shapes and
    flag derivation as ``work_demo`` with the solves' analytical
    reports built directly from layout math and the hwcost pin fed a
    modeled cost — everything the CHECKER judges is real (inventories,
    verdicts, recorder events); only the executables are elided, so
    the fixture costs milliseconds instead of six compiles.  The slow
    acceptance test below runs the real thing."""
    mark = RECORDER.total
    legs = []
    for name, engine, lay, k in [
            ("1d_p4_inplace_gathered", "inplace",
             CyclicLayout.create(44, 8, 4), 0),
            ("1d_p4_swapfree_sharded", "swapfree",
             CyclicLayout.create(44, 8, 4), 0),
            ("1d_p4_inplace_aligned", "inplace",
             CyclicLayout.create(64, 8, 4), 0),
            ("2d_2x2_inplace_gathered", "inplace",
             CyclicLayout2D.create(44, 8, 2, 2), 0),
            ("1d_p4_solve_gathered", "solve_sharded",
             CyclicLayout.create(44, 8, 4), 3),
            ("2d_2x2_solve_sharded", "solve_sharded",
             CyclicLayout2D.create(44, 8, 2, 2), 2)]:
        rep = work.engine_report(engine=engine, lay=lay, k=k,
                                 dtype=jnp.float32)
        rep.attach_xla(_fake_cost(rep))
        legs.append({"name": name, "n": lay.n, "block_size": lay.m,
                     "work": rep.to_json()})
    fleet_legs, fleet = work._fleet_skew_legs()
    blackbox = RECORDER.dump(events=RECORDER.since(mark))
    straggler_events = [e for e in blackbox["events"]
                        if e["kind"] == "straggler_suspected"]
    cleared = [e for e in blackbox["events"]
               if e["kind"] == "straggler_cleared"]
    unaccounted = [leg["name"] for leg in legs
                   if not leg["work"]["totals"]["exact"]]
    xla_unreconciled = [leg["name"] for leg in legs
                        if not leg["work"]["xla"]["within"]]
    aligned = next(leg for leg in legs
                   if leg["name"] == "1d_p4_inplace_aligned")
    penalty_bad = aligned["work"]["totals"]["ragged_penalty"] != 0.0
    verdict_wrong = [
        leg["name"] for leg in fleet_legs
        if bool(leg["verdict"]["suspected"]) != leg["expect_suspected"]]
    return json.loads(json.dumps({
        "metric": "work_demo", "n": 44, "aligned_n": 64,
        "block_size": 8, "dtype": "float32", "generator": "absdiff",
        "ragged": True, "legs": legs, "fleet_legs": fleet_legs,
        "fleet": fleet, "straggler_events": len(straggler_events),
        "cleared_events": len(cleared), "unaccounted": unaccounted,
        "xla_unreconciled": xla_unreconciled,
        "penalty_nonzero_aligned": penalty_bad,
        "verdict_wrong": verdict_wrong,
        "silent_work": bool(unaccounted or xla_unreconciled
                            or penalty_bad or verdict_wrong
                            or not straggler_events),
        "blackbox": blackbox,
    }))


class TestDemoAndChecker:
    def test_checker_accepts_clean_report(self, demo_report, tmp_path):
        errs, silent = check_work.check(demo_report)
        assert errs == [] and silent == []
        p = tmp_path / "work.json"
        p.write_text(json.dumps(demo_report))
        assert check_work.main([str(p)]) == 0

    def test_checker_rejects_silent_share_shift(self, demo_report):
        """Doctored: work shifted between workers with the totals still
        summing — the checker re-derives every share from layout math
        and exit-2s, never trusting the exact flag."""
        doc = json.loads(json.dumps(demo_report))
        pw = doc["legs"][0]["work"]["per_worker"]
        pw["0"]["eliminate"] += 4096
        pw["1"]["eliminate"] -= 4096
        errs, silent = check_work.check(doc)
        assert any("layout-derived" in s for s in silent)

    def test_checker_rejects_hidden_xla_overrun(self, demo_report):
        doc = json.loads(json.dumps(demo_report))
        x = doc["legs"][0]["work"]["xla"]
        x["per_device_flops"] *= 10
        x["total_flops"] *= 10
        x["xla_vs_model"] *= 10
        errs, silent = check_work.check(doc)
        assert any("UNACCOUNTED work" in s for s in silent)

    def test_checker_rejects_unsupported_verdict(self, demo_report):
        doc = json.loads(json.dumps(demo_report))
        for leg in doc["fleet_legs"]:
            if leg["name"] == "fleet_skew_layout_attributed":
                leg["verdict"]["suspected"] = True
        errs, silent = check_work.check(doc)
        assert any("UNSUPPORTED VERDICT" in s for s in silent)

    def test_checker_rejects_stripped_straggler_event(self,
                                                      demo_report):
        doc = json.loads(json.dumps(demo_report))
        doc["blackbox"]["events"] = [
            e for e in doc["blackbox"]["events"]
            if e["kind"] != "straggler_suspected"]
        doc["straggler_events"] = 0
        errs, silent = check_work.check(doc)
        assert any("SILENT STRAGGLER" in s for s in silent)

    def test_checker_rejects_nonzero_aligned_penalty(self, demo_report):
        doc = json.loads(json.dumps(demo_report))
        leg = next(l for l in doc["legs"]
                   if l["name"] == "1d_p4_inplace_aligned")
        leg["work"]["totals"]["ragged_penalty"] = 0.05
        errs, silent = check_work.check(doc)
        assert silent or errs

    def test_checker_exit_taxonomy(self, demo_report, tmp_path):
        foreign = tmp_path / "foreign.json"
        foreign.write_text(json.dumps({"metric": "comm_demo"}))
        assert check_work.main([str(foreign)]) == 1
        doc = json.loads(json.dumps(demo_report))
        doc["legs"][0]["work"]["per_worker"]["0"]["pivot"] += 1
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(doc))
        assert check_work.main([str(bad)]) == 2
        assert check_work.main([str(tmp_path / "missing.json")]) == 1

    @pytest.mark.slow  # tier-1 budget: six compiles; the synthetic
    def test_real_demo_is_clean(self):   # fixture pins the checker fast
        report = work.work_demo(n=28, block_size=8)
        assert report["silent_work"] is False
        assert report["ragged"] is True
        errs, silent = check_work.check(report)
        assert errs == [] and silent == []

    def test_demo_refuses_complex_dtype(self):
        from tpu_jordan.driver import UsageError

        with pytest.raises(UsageError):
            work.work_demo(n=28, block_size=8, dtype="complex64")
