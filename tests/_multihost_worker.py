"""Worker process for the 2-process jax.distributed test (not collected
by pytest — launched by tests/test_multihost.py).

The analog of one MPI rank under ``mpirun -np 2`` (MPI_Init,
main.cpp:69): each process owns 4 virtual CPU devices; after
``distributed_init`` the global mesh spans all 8 and the same sharded
solve code runs unchanged, collectives crossing the process boundary.
"""

import sys


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    import jax

    from tpu_jordan.parallel.mesh import distributed_init

    distributed_init(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == 4 * nproc, jax.device_count()

    from tpu_jordan.driver import solve

    # gather=False keeps every array sharded (nothing must be fully
    # addressable on one process); the residual is a replicated scalar.
    # Thresholds are relative to ‖A‖∞ ≈ n²/2 for the |i−j| generator (the
    # raw residual is unnormalized, reference convention).
    res = solve(64, 8, workers=8, gather=False)
    assert res.residual / (64 * 64 / 2) < 1e-4, f"1D residual {res.residual}"
    res2 = solve(48, 8, workers=(2, 4), gather=False)
    assert res2.residual / (48 * 48 / 2) < 1e-4, f"2D residual {res2.residual}"
    # File input: every process streams the shared file and places only
    # its addressable strips (read_matrix multi-rank parity,
    # main.cpp:242-276).
    resf = solve(64, 8, file=sys.argv[4], workers=8, gather=False)
    assert resf.residual / 32 < 5e-3, f"file residual {resf.residual}"
    print(f"MULTIHOST-OK rank={pid} res1d={res.residual:.2e} "
          f"res2d={res2.residual:.2e} resfile={resf.residual:.2e}",
          flush=True)


if __name__ == "__main__":
    main()
