"""Streaming file scatter (VERDICT r2 #4): host memory O(n·m), shard
formats identical to the host-array scatters, full driver solves from a
file with the whole-matrix host parse forbidden."""

import numpy as np
import pytest

import jax.numpy as jnp

import tpu_jordan.driver as driver_mod
import tpu_jordan.io as io_mod
from tpu_jordan.io import (
    MatrixReadError,
    MatrixStripReader,
    read_matrix_corner,
    write_matrix_file,
)
from tpu_jordan.parallel import make_mesh, make_mesh_2d
from tpu_jordan.parallel.layout import CyclicLayout, CyclicLayout2D
from tpu_jordan.parallel.scatter_stream import (
    stream_scatter_1d,
    stream_scatter_2d,
)


@pytest.fixture
def matrix_file(tmp_path, rng):
    def make(n):
        a = rng.standard_normal((n, n))
        path = str(tmp_path / f"m{n}.txt")
        write_matrix_file(path, a)
        return path, a
    return make


class TestStripReader:
    def test_reads_strips(self, matrix_file):
        path, a = matrix_file(12)
        with MatrixStripReader(path, 12) as r:
            top = r.read_rows(5)
            rest = r.read_rows(7)
        np.testing.assert_allclose(np.vstack([top, rest]), a, rtol=1e-12)

    def test_short_file_raises(self, tmp_path):
        p = tmp_path / "short.txt"
        p.write_text("1.0 2.0 3.0\n")
        with MatrixStripReader(str(p), 4) as r:
            with pytest.raises(MatrixReadError):
                r.read_rows(4)

    def test_missing_file_raises(self):
        with pytest.raises(FileNotFoundError):
            MatrixStripReader("/nonexistent/m.txt", 4)

    def test_python_fallback_chunk_boundaries(self, matrix_file,
                                              monkeypatch):
        # Force the pure-Python tokenizer with a pathological chunk size
        # so numbers straddle every chunk boundary.
        path, a = matrix_file(6)
        monkeypatch.setattr(MatrixStripReader, "_CHUNK", 7)
        r = MatrixStripReader.__new__(MatrixStripReader)
        r.path, r.n, r.dtype = path, 6, np.float64
        r._native, r._tail, r._pending = None, "", []
        r._fh = open(path)
        got = r.read_rows(6)
        r.close()
        np.testing.assert_allclose(got, a, rtol=1e-12)

    def test_corner(self, matrix_file):
        path, a = matrix_file(16)
        c = read_matrix_corner(path, 16)
        np.testing.assert_allclose(c, a[:10, :10], rtol=1e-6)


class TestShardFormatParity:
    """The streamed shards must be byte-identical to the host-array
    scatters the engines were compiled against."""

    @pytest.mark.parametrize("n,m,p", [(20, 4, 4), (18, 4, 4), (32, 8, 2)])
    @pytest.mark.parametrize("augmented", [False, True])
    def test_1d(self, matrix_file, n, m, p, augmented):
        from tpu_jordan.parallel.ring_gemm import _to_identity_padded_blocks
        from tpu_jordan.parallel.sharded_jordan import scatter_augmented

        path, a = matrix_file(n)
        mesh = make_mesh(p)
        lay = CyclicLayout.create(n, m, p)
        got = stream_scatter_1d(path, lay, mesh, jnp.float32, augmented)
        aj = jnp.asarray(a, jnp.float32)
        want = (scatter_augmented(aj, lay, mesh) if augmented
                else _to_identity_padded_blocks(aj, lay, mesh))
        assert got.sharding == want.sharding
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("pr,pc", [(2, 4), (2, 2)])
    @pytest.mark.parametrize("augmented", [False, True])
    def test_2d(self, matrix_file, pr, pc, augmented):
        from tpu_jordan.parallel.jordan2d import (
            scatter_augmented_2d,
            scatter_matrix_2d,
        )

        n, m = 20, 4
        path, a = matrix_file(n)
        mesh = make_mesh_2d(pr, pc)
        lay = CyclicLayout2D.create(n, m, pr, pc)
        got = stream_scatter_2d(path, lay, mesh, jnp.float32, augmented)
        aj = jnp.asarray(a, jnp.float32)
        want = (scatter_augmented_2d(aj, lay, mesh) if augmented
                else scatter_matrix_2d(aj, lay, mesh))
        assert got.sharding == want.sharding
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)


class TestDriverFileStreaming:
    @pytest.fixture(autouse=True)
    def forbid_full_parse(self, monkeypatch):
        # The whole point (main.cpp:242-276 parity): a distributed file
        # solve must never parse the whole file into a host n x n array.
        def boom(*a, **k):
            raise AssertionError("full-matrix host parse on the "
                                 "streaming path")
        monkeypatch.setattr(io_mod, "read_matrix_file", boom)
        monkeypatch.setattr(driver_mod, "read_matrix_file", boom)

    @pytest.mark.parametrize("workers", [
        4,
        # tier-1 budget: the 2D file-solve leg duplicates the 1D one
        # through the same streaming scatter path and runs nightly.
        pytest.param((2, 2), marks=pytest.mark.slow)])
    @pytest.mark.parametrize("gather", [True, False])
    def test_distributed_file_solve(self, matrix_file, workers, gather):
        path, a = matrix_file(32)
        res = driver_mod.solve(32, 8, file=path, workers=workers,
                               gather=gather)
        assert res.residual < 1e-3
        if gather:
            np.testing.assert_allclose(
                np.asarray(res.inverse), np.linalg.inv(a),
                rtol=1e-2, atol=1e-3)
        else:
            assert res.inverse is None
            assert res.inverse_blocks is not None

    def test_file_corner_print(self, matrix_file, capsys):
        path, a = matrix_file(32)
        driver_mod.solve(32, 8, file=path, workers=4, verbose=True)
        out = capsys.readouterr().out
        assert "residual" in out
