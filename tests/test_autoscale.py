"""FleetAutoscaler policy coverage (ISSUE 18 tentpole part 2): the
fake-clock control loop driven tick-by-tick against a scripted fake
pool/registry (scale-up on sustained two-window burn, the capacity-
ledger veto as a typed ``scale_withheld``, cooldown spacing, ceiling/
floor bounds, drain on idle, the pre-shed flag engaging the tick risk
appears and releasing the tick it clears — never draining into a
burn), plus the ``--autoscale-demo`` acceptance run validated by the
SAME checker ``make autoscale-demo`` runs (accept + doctored-reject:
stripped burn evidence, a silent p99 breach, an uncounted pre-shed,
and a diverged flight-recorder trail must all page)."""

import copy
import importlib.util
import pathlib
import types

import pytest

from tpu_jordan.fleet import FleetAutoscaler, autoscale_demo
from tpu_jordan.obs.metrics import REGISTRY
from tpu_jordan.obs.recorder import RECORDER
from tpu_jordan.obs.slo import SLOMonitor, SLOSpec

_tool = (pathlib.Path(__file__).resolve().parent.parent / "tools"
         / "check_autoscale.py")
_spec = importlib.util.spec_from_file_location("check_autoscale", _tool)
check_autoscale = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_autoscale)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


class FakeRegistry:
    """A scripted metrics source: the test mutates ``ok``/``err``/
    ``p99_s`` between ticks and ``snapshot()`` renders exactly the two
    series the burn windows and the p99 objective integrate."""

    def __init__(self, bucket="64"):
        self.bucket = bucket
        self.ok = 0
        self.err = 0
        self.p99_s = None

    def snapshot(self):
        snap = {"tpu_jordan_request_outcome_total": {"series": [
            {"labels": {"bucket": self.bucket, "outcome": "ok"},
             "value": float(self.ok)},
            {"labels": {"bucket": self.bucket, "outcome": "error"},
             "value": float(self.err)},
        ]}}
        if self.p99_s is not None:
            snap["tpu_jordan_request_latency_seconds"] = {"series": [
                {"labels": {"bucket": self.bucket}, "p99": self.p99_s}]}
        return snap


class FakePool:
    """The four-method harness the autoscaler docstring names: ready
    count, grow, drain, and the router's pre-shed flag."""

    def __init__(self, ready=1):
        self._ready = int(ready)
        self.router = types.SimpleNamespace(pre_shed=False)
        self.grown = 0
        self.drained = 0

    def ready_count(self):
        return self._ready

    def grow(self):
        self._ready += 1
        self.grown += 1
        return self._ready - 1

    def drain_slot(self):
        self._ready -= 1
        self.drained += 1
        return self._ready


def _harness(ready=1, availability=0.9, p99_ms=100.0, floor=1,
             ceiling=3, idle_after_s=5.0, cooldown=0.0, **kw):
    """One (clock, registry, pool, scaler) with a (10s, 2s, 1x) burn
    pair: 50% errors against a 0.1 budget burns 5x — decisively
    paging; zero traffic burns zero — decisively quiet."""
    clock = FakeClock()
    reg = FakeRegistry()
    monitor = SLOMonitor(
        [SLOSpec(name="demo", bucket="64", availability=availability,
                 p99_latency_ms=p99_ms)],
        registry=reg, clock=clock, windows=((10.0, 2.0, 1.0),))
    pool = FakePool(ready=ready)
    scaler = FleetAutoscaler(pool, monitor, floor=floor,
                             ceiling=ceiling,
                             idle_after_s=idle_after_s,
                             scale_cooldown_s=cooldown, clock=clock,
                             **kw)
    return clock, reg, pool, scaler


class TestAutoscalerPolicy:
    def test_full_cycle_scale_up_preshed_drain_to_floor(self):
        """The whole loop on a fake clock: quiet baseline -> sustained
        burn scales to the ceiling with pre-shed engaged -> the burn
        clearing drains back to the floor with pre-shed released —
        and every action's evidence re-derives under the SAME checker
        the CI gate runs."""
        clock, reg, pool, scaler = _harness()
        mark = RECORDER.total
        c = REGISTRY.counter("tpu_jordan_autoscale_actions_total")
        up0 = c.value(action="scale_up")

        t = scaler.tick()                    # quiet baseline
        assert t["action"] is None and not t["paging"]
        assert pool.router.pre_shed is False

        reg.ok, reg.err = 5, 5               # 50% errors: burn 5x
        clock.advance(1.0)
        t = scaler.tick()
        assert t["action"] == "scale_up" and t["paging"] == ["demo"]
        assert pool.router.pre_shed is True and t["ready"] == 2

        clock.advance(1.0)
        t = scaler.tick()                    # still burning: one more
        assert t["action"] == "scale_up" and t["ready"] == 3

        clock.advance(1.0)
        t = scaler.tick()                    # short window aged out:
        assert t["action"] is None and t["ready"] == 3
        # ...the multi-window AND stops paging (the blip is no longer
        # "still happening") and pre-shed releases immediately, while
        # the fleet holds its scaled size until the idle drain.
        assert not t["paging"] and pool.router.pre_shed is False

        clock.advance(11.0)                  # burn ages out of 10s
        t = scaler.tick()                    # idle >= 5s: drain
        assert t["action"] == "drain" and not t["paging"]
        assert t["ready"] == 2

        clock.advance(1.0)
        t = scaler.tick()
        assert t["action"] == "drain" and t["ready"] == 1

        clock.advance(1.0)
        t = scaler.tick()                    # at the floor: held
        assert t["action"] is None and t["ready"] == 1

        assert [a["action"] for a in scaler.actions] == [
            "scale_up", "pre_shed_on", "scale_up", "pre_shed_off",
            "drain", "drain"]
        assert pool.grown == 2 and pool.drained == 2
        assert c.value(action="scale_up") - up0 == 2
        # The flight-recorder trail mirrors the in-memory one.
        events = [e for e in RECORDER.since(mark)
                  if e.get("kind") == "autoscale"]
        assert ([e["action"] for e in events]
                == [a["action"] for a in scaler.actions])
        # Each scale_up's evidence re-derives under the CI checker:
        # every recorded window pair actually pages by its own numbers
        # with burn = error_rate / error_budget.
        for a in scaler.actions:
            if a["action"] == "scale_up":
                assert check_autoscale._check_paging_evidence(
                    "t", a["evidence"]["paging"]) == []
            if a["action"] == "drain":
                assert (a["evidence"]["idle_s"]
                        >= a["evidence"]["idle_after_s"])

    def test_cooldown_spaces_capacity_actions(self):
        clock, reg, pool, scaler = _harness(cooldown=100.0)
        scaler.tick()
        reg.ok, reg.err = 5, 5
        clock.advance(1.0)
        assert scaler.tick()["action"] == "scale_up"
        clock.advance(1.0)
        t = scaler.tick()                    # paging, but in cooldown
        assert t["action"] is None and t["paging"] == ["demo"]
        assert t["pre_shed"] is True         # the flag has no cooldown
        assert pool.grown == 1

    def test_capacity_veto_records_scale_withheld(self, monkeypatch):
        clock, reg, pool, scaler = _harness(scale_budget_bytes=1000)
        monkeypatch.setattr("tpu_jordan.obs.capacity.live_bytes",
                            lambda *a, **k: 5000)
        scaler.tick()
        reg.ok, reg.err = 5, 5
        clock.advance(1.0)
        t = scaler.tick()
        assert t["action"] == "scale_withheld"
        assert pool.grown == 0 and t["ready"] == 1
        ev = scaler.actions[0]["evidence"]
        assert ev["live_bytes"] >= ev["scale_budget_bytes"]
        assert check_autoscale._check_paging_evidence(
            "t", ev["paging"]) == []

    def test_p99_risk_presheds_and_blocks_drain_until_clear(self):
        """p99 at 90% of a 100ms target with a 0.8 trigger: pre-shed
        engages WITHOUT a burn, and an otherwise-idle fleet must not
        drain into the risk; the risk clearing releases the flag and
        the drain lands the same tick."""
        clock, reg, pool, scaler = _harness(ready=2, idle_after_s=0.0)
        reg.p99_s = 0.090                    # 90ms >= 0.8 x 100ms
        t = scaler.tick()
        assert t["p99_risk"] == ["demo"] and not t["paging"]
        assert t["pre_shed"] is True and t["action"] is None
        assert pool.drained == 0
        on = scaler.actions[0]
        assert on["action"] == "pre_shed_on"
        assert on["evidence"]["p99_risk"][0]["p99_ms"] >= 80.0

        reg.p99_s = 0.010
        clock.advance(1.0)
        t = scaler.tick()
        assert t["action"] == "drain" and t["pre_shed"] is False
        assert [a["action"] for a in scaler.actions] == [
            "pre_shed_on", "drain", "pre_shed_off"]

    def test_skew_judge_vetoes_p99_preshed_not_paging(self):
        """ISSUE 19: with a suspected straggler on the fleet-skew
        judge, p99-risk-driven pre-shed is withheld (the veto evidence
        rides in the tick and a transition-only pre_shed_vetoed
        action), while paging-driven pre-shed engages regardless —
        burn is fleet-wide evidence."""
        from tpu_jordan.obs.work import FleetSkewJudge

        judge = FleetSkewJudge()
        judge.assess({"0": 10.0, "1": 10.0, "2": 55.0})
        assert judge.veto() is not None
        clock, reg, pool, scaler = _harness(ready=2, idle_after_s=0.0,
                                            skew_judge=judge)
        reg.p99_s = 0.090                    # p99 risk, no burn
        t = scaler.tick()
        assert t["p99_risk"] == ["demo"] and not t["paging"]
        assert t["pre_shed"] is False        # vetoed, not engaged
        assert t["skew_veto"]["replica"] == "2"
        assert t["skew_veto"]["spread"] > t["skew_veto"]["threshold"]
        assert [a["action"] for a in scaler.actions] == [
            "pre_shed_vetoed"]               # transition-only
        clock.advance(1.0)
        t = scaler.tick()                    # still vetoed: no repeat
        assert t["pre_shed"] is False
        assert [a["action"] for a in scaler.actions] == [
            "pre_shed_vetoed"]

        reg.ok, reg.err = 5, 5               # now a real burn pages
        clock.advance(1.0)
        t = scaler.tick()
        assert t["paging"] == ["demo"] and t["pre_shed"] is True
        assert "skew_veto" not in t

        # The straggler clearing re-arms p99-driven shedding.
        judge.assess({"0": 10.0, "1": 10.0, "2": 11.0})
        assert judge.veto() is None
        reg.ok, reg.err = 10, 0
        clock.advance(20.0)
        scaler.tick()                        # burn window clears
        clock.advance(1.0)
        t = scaler.tick()
        assert t["p99_risk"] == ["demo"] and t["pre_shed"] is True

    def test_drain_never_below_floor_scale_never_above_ceiling(self):
        clock, reg, pool, scaler = _harness(ready=1, idle_after_s=0.0,
                                            floor=1, ceiling=2)
        scaler.tick()
        clock.advance(1.0)
        assert scaler.tick()["action"] is None     # idle at the floor
        reg.ok, reg.err = 5, 5
        clock.advance(1.0)
        assert scaler.tick()["action"] == "scale_up"
        clock.advance(1.0)
        assert scaler.tick()["action"] is None     # at the ceiling
        assert pool.ready_count() == 2

    def test_ctor_validates_bounds(self):
        clock, reg, pool, scaler = _harness()
        with pytest.raises(ValueError, match="floor"):
            FleetAutoscaler(pool, scaler.monitor, floor=0)
        with pytest.raises(ValueError, match="ceiling"):
            FleetAutoscaler(pool, scaler.monitor, floor=3, ceiling=2)


#: One cached acceptance run (the Makefile's exact shape) shared by the
#: pin + every doctored-reject: the checker tests doctor COPIES instead
#: of paying for a second burst->idle->recovery trace.
_REPORT_CACHE = {}


def _report():
    if "report" not in _REPORT_CACHE:
        _REPORT_CACHE["report"] = autoscale_demo(
            n=48, requests=32, floor=1, ceiling=3, batch_cap=4,
            block_size=16)
    return _REPORT_CACHE["report"]


class TestAutoscaleDemoAcceptance:
    def test_demo_exercises_the_loop_and_checker_accepts(self):
        """The ISSUE 18 acceptance pin: the demo shows scale-up on
        burn, a pre-shed engage/release cycle, drain back to the
        floor, a clean recovery — and the CI checker re-derives every
        decision from its recorded burn evidence with zero silent
        breaches (the same validation ``make autoscale-demo`` runs)."""
        report = _report()
        assert check_autoscale.check(report) == ([], [])
        kinds = report["actions_by_kind"]
        assert kinds.get("scale_up", 0) >= 1
        assert kinds.get("drain", 0) >= 1
        assert kinds.get("pre_shed_on", 0) >= 1
        assert kinds.get("pre_shed_off", 0) >= 1
        assert report["silent_p99_breach"] is False
        traj = report["ready_trajectory"]
        assert max(traj) <= report["ceiling"]
        assert min(traj) >= report["floor"]
        assert traj[-1] == report["floor"]
        assert report["pre_shed_count"] >= 1
        assert report["ledger"]["outstanding"] == 0
        # The burn source is typed, deterministic deadline pressure.
        burst = report["phases"]["burst"]["waves"]
        assert any(w["typed_errors"].get("DeadlineExceededError")
                   for w in burst)
        assert report["phases"]["recovery"]["ok"] >= 1
        assert not report["phases"]["recovery"]["typed_errors"]

    def test_checker_pages_on_stripped_burn_evidence(self):
        doctored = copy.deepcopy(_report())
        up = next(a for a in doctored["actions"]
                  if a["action"] == "scale_up")
        up["evidence"]["paging"] = []
        errs, silent = check_autoscale.check(doctored)
        assert any("unexplained" in s for s in silent)

    def test_checker_pages_on_silent_p99_breach(self):
        doctored = copy.deepcopy(_report())
        tick = next(t for t in doctored["ticks"]
                    if t["pre_shed"] and (t["paging"] or t["p99_risk"])
                    and t["action"] is None)
        tick["pre_shed"] = False
        errs, silent = check_autoscale.check(doctored)
        assert any("SILENT P99 BREACH" in s for s in silent)
        # The report's own flag now disagrees with the re-derivation —
        # a second, independent alarm.
        assert any("disagrees" in s for s in silent)

    def test_checker_honors_skew_vetoed_tick(self):
        """ISSUE 19, trapped both ways: a risk tick with pre-shed OFF
        is the breach class — unless it carries supported skew-veto
        evidence; a veto whose evidence does not re-derive (spread
        under threshold) still pages."""
        doctored = copy.deepcopy(_report())
        tick = next(t for t in doctored["ticks"]
                    if t["pre_shed"] and (t["paging"] or t["p99_risk"])
                    and t["action"] is None)
        tick["pre_shed"] = False
        tick["skew_veto"] = {"replica": "2", "spread": 5.5,
                             "threshold": 2.0}
        doctored["silent_p99_breach"] = False
        errs, silent = check_autoscale.check(doctored)
        assert not any("SILENT P99 BREACH" in s for s in silent)
        # The other way: a pre_shed_vetoed action whose evidence does
        # not support the veto is itself the exit-2 class.
        doctored["actions"].append({
            "action": "pre_shed_vetoed", "ready_before": 2,
            "ready_after": 2,
            "evidence": {"p99_risk": [{"name": "demo"}],
                         "skew_veto": {"replica": "2", "spread": 1.2,
                                       "threshold": 2.0}}})
        errs2, silent2 = check_autoscale.check(doctored)
        assert any("veto evidence does not re-derive" in s.lower()
                   for s in silent2)

    def test_checker_pages_on_uncounted_preshed(self):
        doctored = copy.deepcopy(_report())
        doctored["pre_shed_count"] += 1
        errs, silent = check_autoscale.check(doctored)
        assert any("uncounted or unhopped" in s for s in silent)

    def test_checker_pages_on_diverged_recorder_trail(self):
        doctored = copy.deepcopy(_report())
        events = doctored["blackbox"]["events"]
        drop = next(e for e in events if e.get("kind") == "autoscale")
        events.remove(drop)
        errs, silent = check_autoscale.check(doctored)
        assert any("diverge" in s for s in silent)

    def test_checker_fails_vacuous_or_foreign_reports(self):
        errs, _ = check_autoscale.check({"metric": "serve_demo"})
        assert errs
        doctored = copy.deepcopy(_report())
        doctored["actions"] = [a for a in doctored["actions"]
                               if a["action"] != "drain"]
        errs, silent = check_autoscale.check(doctored)
        assert any("no drain action" in e for e in errs)
