"""Mixed-precision policy: HIGH sweeps + HIGHEST refinement, bf16 dtype
support, and the precision plumbing through driver/solver/CLI.

Note: on CPU every Precision level is computed identically, so these tests
pin the *plumbing and contract*; the accuracy ladder itself is measured on
TPU and recorded in benchmarks/PHASES.md.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.driver import solve
from tpu_jordan.models import JordanSolver
from tpu_jordan.ops import (
    block_jordan_invert,
    block_jordan_invert_inplace,
    generate,
    inf_norm,
    residual_inf_norm,
)
from tpu_jordan.ops.refine import resolve_precision


def test_resolve_precision_mixed():
    from jax import lax

    p, r = resolve_precision("mixed", 0)
    assert p == lax.Precision.HIGH and r == 2
    p, r = resolve_precision("mixed", 5)
    assert p == lax.Precision.HIGH and r == 5
    p, r = resolve_precision(lax.Precision.HIGHEST, 1)
    assert p == lax.Precision.HIGHEST and r == 1


@pytest.mark.parametrize("fn", [block_jordan_invert,
                                block_jordan_invert_inplace])
def test_mixed_inverts_accurately(rng, fn):
    a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
    inv, sing = fn(a, block_size=16, precision="mixed")
    assert not bool(sing)
    rel = float(residual_inf_norm(a, inv)) / float(inf_norm(a))
    assert rel < 1e-5


def test_solve_mixed_single_device():
    res = solve(n=96, block_size=16, precision="mixed")
    assert res.residual / (96 * 96 / 2) < 1e-5


def test_solve_mixed_distributed():
    res = solve(n=96, block_size=8, workers=4, precision="mixed")
    assert res.residual / (96 * 96 / 2) < 1e-5


@pytest.mark.slow
def test_solve_mixed_2d():
    res = solve(n=96, block_size=8, workers=(2, 2), precision="mixed")
    assert res.residual / (96 * 96 / 2) < 1e-5


def test_solver_mixed_forces_refine():
    s = JordanSolver(n=32, precision="mixed")
    assert s.refine == 2


def test_bfloat16_dtype_end_to_end(rng):
    # bf16 storage: the probe upcasts to fp32 internally; the result comes
    # back in bf16.  Accuracy is bf16-grade — assert the loose bound.
    a = jnp.asarray(rng.standard_normal((64, 64)), jnp.bfloat16)
    inv, sing = block_jordan_invert(a, block_size=16, refine=2)
    assert inv.dtype == jnp.bfloat16
    assert not bool(sing)
    af = np.asarray(a, np.float64)
    rel = (np.max(np.sum(np.abs(af @ np.asarray(inv, np.float64)
                                 - np.eye(64)), axis=1))
           / np.max(np.sum(np.abs(af), axis=1)))
    assert rel < 0.1


def test_bfloat16_distributed_computes_fp32(rng):
    # Distributed sub-fp32 must follow the same fp32-compute policy as
    # the single-device kernels; result comes back bf16-rounded with an
    # honest (post-rounding) residual.
    res = solve(n=64, block_size=8, workers=4, dtype=jnp.bfloat16)
    assert res.inverse.dtype == jnp.bfloat16
    af = np.asarray(generate("absdiff", (64, 64), jnp.float32), np.float64)
    rel = res.residual / np.max(np.sum(np.abs(af), axis=1))
    assert rel < 0.1


def test_mixed_gather_false_rejected():
    with pytest.raises(ValueError, match="mixed"):
        solve(n=64, block_size=8, workers=4, precision="mixed",
              gather=False)


def test_cli_precision_flag():
    from tpu_jordan.__main__ import main

    assert main(["64", "16", "--precision", "mixed", "--quiet"]) == 0
