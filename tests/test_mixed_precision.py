"""Mixed-precision policy: HIGH sweeps + HIGHEST refinement, bf16 dtype
support, and the precision plumbing through driver/solver/CLI.

Note: on CPU every Precision level is computed identically, so these tests
pin the *plumbing and contract*; the accuracy ladder itself is measured on
TPU and recorded in benchmarks/PHASES.md.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.driver import solve
from tpu_jordan.models import JordanSolver
from tpu_jordan.ops import (
    block_jordan_invert,
    block_jordan_invert_inplace,
    generate,
    inf_norm,
    residual_inf_norm,
)
from tpu_jordan.ops.refine import resolve_precision


def test_resolve_precision_mixed():
    from jax import lax

    p, r = resolve_precision("mixed", 0)
    assert p == lax.Precision.HIGH and r == 2
    p, r = resolve_precision("mixed", 5)
    assert p == lax.Precision.HIGH and r == 5
    p, r = resolve_precision(lax.Precision.HIGHEST, 1)
    assert p == lax.Precision.HIGHEST and r == 1


@pytest.mark.parametrize("fn", [block_jordan_invert,
                                block_jordan_invert_inplace])
def test_mixed_inverts_accurately(rng, fn):
    a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
    inv, sing = fn(a, block_size=16, precision="mixed")
    assert not bool(sing)
    rel = float(residual_inf_norm(a, inv)) / float(inf_norm(a))
    assert rel < 1e-5


def test_solve_mixed_single_device():
    res = solve(n=96, block_size=16, precision="mixed")
    assert res.residual / (96 * 96 / 2) < 1e-5


@pytest.mark.slow  # tier-1 budget: the single-device + 2D mixed-solve siblings stay
def test_solve_mixed_distributed():
    res = solve(n=96, block_size=8, workers=4, precision="mixed")
    assert res.residual / (96 * 96 / 2) < 1e-5


@pytest.mark.slow
def test_solve_mixed_2d():
    res = solve(n=96, block_size=8, workers=(2, 2), precision="mixed")
    assert res.residual / (96 * 96 / 2) < 1e-5


def test_solver_mixed_forces_refine():
    s = JordanSolver(n=32, precision="mixed")
    assert s.refine == 2


def test_bfloat16_dtype_end_to_end(rng):
    # bf16 storage: the probe upcasts to fp32 internally; the result comes
    # back in bf16.  Accuracy is bf16-grade — assert the loose bound.
    a = jnp.asarray(rng.standard_normal((64, 64)), jnp.bfloat16)
    inv, sing = block_jordan_invert(a, block_size=16, refine=2)
    assert inv.dtype == jnp.bfloat16
    assert not bool(sing)
    af = np.asarray(a, np.float64)
    rel = (np.max(np.sum(np.abs(af @ np.asarray(inv, np.float64)
                                 - np.eye(64)), axis=1))
           / np.max(np.sum(np.abs(af), axis=1)))
    assert rel < 0.1


@pytest.mark.slow  # tier-1 budget: the distributed sub-fp32 upcast-policy
# pins in test_sharded_inplace/test_jordan2d_inplace and the solver
# storage-dtype test keep fast-run coverage
def test_bfloat16_distributed_computes_fp32(rng):
    # Distributed sub-fp32 must follow the same fp32-compute policy as
    # the single-device kernels; result comes back bf16-rounded with an
    # honest (post-rounding) residual.
    res = solve(n=64, block_size=8, workers=4, dtype=jnp.bfloat16)
    assert res.inverse.dtype == jnp.bfloat16
    af = np.asarray(generate("absdiff", (64, 64), jnp.float32), np.float64)
    rel = res.residual / np.max(np.sum(np.abs(af), axis=1))
    assert rel < 0.1


def test_mixed_gather_false_rejected():
    with pytest.raises(ValueError, match="mixed"):
        solve(n=64, block_size=8, workers=4, precision="mixed",
              gather=False)


def test_cli_precision_flag():
    from tpu_jordan.__main__ import main

    assert main(["64", "16", "--precision", "mixed", "--quiet"]) == 0


class TestGroupedPallasBf16Path:
    """ISSUE 6: the bf16-compute/fp32-accumulate fused-kernel path, end
    to end through the driver — every bf16 result either passes the
    residual gate or carries a recovery record, never a silent degraded
    inverse (the arXiv:2112.09017 bf16 + iterative-refinement recipe
    with the PR 5 ladder as the safety net)."""

    def _well_conditioned_file(self, tmp_path, n):
        # κ·eps_bf16 << 1 is the precondition for bf16 compute to carry
        # any digits: a dominant diagonal keeps κ∞ at a few.
        from tpu_jordan.io import write_matrix_file

        rng = np.random.default_rng(3)
        a = rng.standard_normal((n, n)) + n * np.eye(n)
        path = str(tmp_path / "wc.mat")
        write_matrix_file(path, a)
        return path

    def test_well_conditioned_passes_gate_zero_rungs(self, tmp_path):
        # The default policy is auto-attached (no policy argument): the
        # gate runs at bf16 eps and a bf16-grade residual on a
        # bf16-well-conditioned matrix is a PASS — zero ladder rungs.
        n = 64
        path = self._well_conditioned_file(tmp_path, n)
        r = solve(n, 16, file=path, engine="grouped_pallas_bf16")
        assert r.engine == "grouped_pallas_bf16"
        assert r.recovery == ()
        assert r.rel_residual < 0.05          # bf16-grade, honest number

    @pytest.mark.slow   # tier-1 keeps the resolve-rung pin below plus
    # PR 5's refine→resolve walk on the generic path
    # (test_resilience.py::test_bf16_fails_gate_recovers_refine_then_fp32)
    def test_ill_conditioned_recovers_refine_or_resolve(self, tmp_path):
        # An fp32-strict accuracy SLO (gate_dtype) on a bf16 solve:
        # the bf16-grade residual fails the gate and the ladder must
        # recover — rungs recorded on SolveResult.recovery, final gate
        # passed, never an exception and never a silent bf16-grade
        # return.
        from tpu_jordan.resilience.policy import ResiliencePolicy

        # gate_tol=1e-3: κ∞ computed from the bf16-grade inverse is
        # inflated ~30x (‖X‖∞ carries the error), which at the default
        # tol=16 pushes even the fp32-eps gate past the bf16 residual;
        # the tighter SLO is the realistic "I need fp32-grade numbers"
        # setting (threshold ≈ 3.6e-3 here vs the bf16 rel ≈ 7.7e-2).
        pol = ResiliencePolicy(gate_dtype="float32", gate_tol=1e-3)
        r = solve(n=96, block_size=16, engine="grouped_pallas_bf16",
                  policy=pol)
        assert len(r.recovery) >= 1
        assert r.recovery[-1]["passed"]
        assert [x["rung"] for x in r.recovery][-1] in ("refine", "resolve")
        # The recovered number is fp32-grade (the SLO's whole point).
        assert r.rel_residual < 1e-3

    def test_resolve_rung_escalates_to_fp32_engine(self):
        # refine_steps=0 forces the ladder straight to the re-solve
        # rung, which must escalate the ENGINE to the fp32 fused-kernel
        # sibling (full-precision dots), recorded with its dtype.
        from tpu_jordan.resilience.policy import ResiliencePolicy

        pol = ResiliencePolicy(gate_dtype="float32", gate_tol=1e-3,
                               refine_steps=0)
        r = solve(n=96, block_size=16, engine="grouped_pallas_bf16",
                  policy=pol)
        assert [x["rung"] for x in r.recovery] == ["resolve"]
        assert r.recovery[0]["passed"]
        assert r.recovery[0]["dtype"] == "float32"
        assert r.rel_residual < 1e-3

    @pytest.mark.slow       # tier-1 keeps the cheap threshold pin below
    def test_no_inverse_never_passes_gate(self):
        # The gaussian fixture at n=96 has κ·eps_bf16 >> 1: bf16
        # compute produces ‖I−AX‖ ≈ ‖I‖ — no inverse.  The 0.5 gate
        # ceiling (resilience/degrade.py) must catch it even at bf16
        # eps, and the auto-attached ladder must deliver a real
        # (recovered) inverse with the walk on record.
        r = solve(n=96, block_size=16, engine="grouped_pallas_bf16",
                  generator="rand")
        assert len(r.recovery) >= 1
        assert r.recovery[-1]["rung"] == "resolve"
        assert r.recovery[-1]["passed"]
        assert r.rel_residual < 1e-3

    def test_gate_threshold_capped(self):
        from tpu_jordan.resilience.degrade import gate_threshold
        from tpu_jordan.resilience.policy import DEFAULT_POLICY

        assert gate_threshold(DEFAULT_POLICY, 96, 1e9,
                              jnp.bfloat16) == 0.5
