"""Unified telemetry layer (ISSUE 4): deterministic fake-clock span
nesting, the process-wide metrics registry, the three exporters
(one-line JSON / Prometheus text / Chrome trace-event JSON), the driver
and serve wiring, and the acceptance pins — a 2D-mesh solve's span tree
carries pivot/permute/eliminate/residual children plus distinct
compile/execute spans, and a warm ``JordanService`` Prometheus scrape
reports ``tpu_jordan_compiles_total`` unchanged across 50 requests.

Everything here is CPU-cheap (tier-1 runs near its 870 s budget); the
one serve round-trip case is the smoke representative.
"""

import importlib.util
import json
import re
import threading
from pathlib import Path

import numpy as np
import pytest

from tpu_jordan.driver import solve
from tpu_jordan.obs import export
from tpu_jordan.obs.metrics import (NAME_RE, REGISTRY, MetricsRegistry,
                                    Reservoir, percentiles)
from tpu_jordan.obs.spans import (NULL, PHASES, Telemetry,
                                  attribute_phases, timed_blocking)

# The Makefile `metrics-demo` checker, loaded from tools/ (not a
# package) so the exporter tests and the CI target share ONE validator.
_CHECKER_PATH = Path(__file__).resolve().parents[1] / "tools" \
    / "check_telemetry.py"
_spec = importlib.util.spec_from_file_location("check_telemetry",
                                               _CHECKER_PATH)
check_telemetry = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_telemetry)


class FakeClock:
    """Deterministic injectable clock: every read advances 1.0 s (the
    tuner's fake-timings discipline applied to spans)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


class TestSpans:
    def test_fake_clock_nesting_deterministic(self):
        tel = Telemetry(clock=FakeClock())
        with tel.span("solve") as root:
            with tel.span("compile"):
                pass
            with tel.span("execute"):
                with tel.span("inner"):
                    pass
        # Clock reads: solve@1, compile@2-3, execute@4, inner@5-6,
        # execute ends@7, solve ends@8 — fully deterministic.
        assert [c.name for c in root.children] == ["compile", "execute"]
        assert root.t_start == 1.0 and root.t_end == 8.0
        assert root.children[0].duration == 1.0
        assert root.children[1].duration == 3.0
        assert root.find("inner").duration == 1.0
        assert tel.roots == [root]

    def test_threads_get_separate_roots(self):
        tel = Telemetry()

        def worker():
            with tel.span("dispatcher"):
                pass

        with tel.span("main"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert sorted(r.name for r in tel.roots) == ["dispatcher", "main"]
        # The worker's span must NOT have nested under "main".
        assert tel.find("main").children == []

    def test_root_retention_is_bounded(self):
        # A long-lived telemetry'd server roots one span per batch —
        # retention must be a window, not unbounded growth.
        tel = Telemetry(clock=FakeClock(), max_roots=3)
        for i in range(7):
            with tel.span(f"r{i}"):
                pass
        assert [r.name for r in tel.roots] == ["r4", "r5", "r6"]

    def test_null_telemetry_measures_but_retains_nothing(self):
        with NULL.span("x") as sp:
            pass
        assert sp.t_end is not None and sp.duration >= 0.0
        assert NULL.roots == []

    def test_timed_blocking_span_is_the_elapsed(self):
        tel = Telemetry(clock=FakeClock())
        out, sp = timed_blocking(lambda: 7, telemetry=tel, name="execute")
        assert out == 7
        assert sp.duration == 1.0
        assert tel.roots[0] is sp

    def test_attribute_phases_partitions_execute(self):
        tel = Telemetry(clock=FakeClock())
        with tel.span("execute") as sp:
            pass
        kids = attribute_phases(sp, n=1024, block_size=128)
        assert [k.name for k in kids] == list(PHASES)
        assert all(k.attrs["modeled"] for k in kids)
        assert kids[0].t_start == sp.t_start
        assert kids[-1].t_end == sp.t_end
        assert abs(sum(k.duration for k in kids) - sp.duration) < 1e-9
        # The 2n³ MXU sweep must dominate the model at any real size.
        assert max(kids, key=lambda k: k.duration).name == "eliminate"


class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("tpu_jordan_test_total", "h")
        c.inc()
        c.inc(2, bucket="64")
        assert c.value() == 1 and c.value(bucket="64") == 2
        assert c.total() == 3
        with pytest.raises(ValueError):
            c.inc(-1)
        g = reg.gauge("tpu_jordan_test_gauge", "h")
        g.set(5)
        g.set(7)
        assert g.value() == 7
        h = reg.histogram("tpu_jordan_test_seconds", "h")
        for v in range(1, 101):
            h.observe(float(v))
        assert h.percentiles() == {"p50": 50.0, "p95": 95.0, "p99": 99.0}
        assert h.percentiles(bucket="none") == {"p50": None, "p95": None,
                                                "p99": None}
        # Histogram.value() is the lifetime sum (never float(Reservoir)).
        assert h.value() == sum(range(1, 101))
        assert h.value(bucket="none") == 0.0

    def test_registration_idempotent_and_kind_conflict(self):
        reg = MetricsRegistry()
        a = reg.counter("tpu_jordan_x_total")
        assert reg.counter("tpu_jordan_x_total") is a
        with pytest.raises(ValueError):
            reg.gauge("tpu_jordan_x_total")

    def test_namespace_lint_at_registration(self):
        reg = MetricsRegistry()
        for bad in ("solves_total", "tpu_jordan_Bad", "tpu_jordan-x",
                    "jordan_tpu_x"):
            with pytest.raises(ValueError):
                reg.counter(bad)
        # The live process registry must already be clean (the conftest
        # session lint re-checks after the whole suite).
        assert all(NAME_RE.match(n) for n in REGISTRY.names())

    def test_reservoir_bounded_window_lifetime_totals(self):
        r = Reservoir(maxlen=4)
        r.extend(range(10))
        assert r.samples == [6.0, 7.0, 8.0, 9.0]
        assert r.count == 10 and r.total == 45.0
        assert percentiles([]) == {"p50": None, "p95": None, "p99": None}


class TestExporters:
    def _registry(self):
        reg = MetricsRegistry()
        c = reg.counter("tpu_jordan_demo_total", "demo counter")
        c.inc(3, bucket="64")
        c.inc(1)
        h = reg.histogram("tpu_jordan_demo_seconds", "demo timing")
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        return reg

    def test_prometheus_text_parses(self):
        text = export.to_prometheus(self._registry())
        lines = text.splitlines()
        assert "# TYPE tpu_jordan_demo_total counter" in lines
        assert "# TYPE tpu_jordan_demo_seconds summary" in lines
        assert 'tpu_jordan_demo_total{bucket="64"} 3' in lines
        assert "tpu_jordan_demo_seconds_count 3" in lines
        # Every sample line parses as name[{labels}] value.
        sample = re.compile(r"^[a-z0-9_]+(\{[^}]*\})? -?[0-9.eE+-]+$")
        for ln in lines:
            if ln and not ln.startswith("#"):
                assert sample.match(ln), ln
        # The Makefile checker accepts the same text (shared validator).
        assert check_telemetry.check_prometheus(text, "<test>") > 0

    def test_chrome_trace_loads_with_matched_events(self):
        tel = Telemetry(clock=FakeClock())
        with tel.span("solve", n=64):
            with tel.span("execute") as ex:
                pass
        attribute_phases(ex, 512, 128)
        text = json.dumps(export.to_chrome_trace(tel))
        doc = json.loads(text)
        evs = doc["traceEvents"]
        assert {e["name"] for e in evs} >= {"solve", "execute", "pivot",
                                            "permute", "eliminate"}
        # Complete events only — each is its own matched begin/end.
        assert all(e["ph"] == "X" and isinstance(e["dur"], (int, float))
                   for e in evs)
        assert check_telemetry.check_chrome_trace(text, "<test>") == len(evs)

    def test_json_line(self):
        line = export.to_json_line(registry=self._registry(), run="r1")
        doc = json.loads(line)
        assert doc["metric"] == "telemetry" and doc["run"] == "r1"
        assert "tpu_jordan_demo_total" in doc["metrics"]
        assert "\n" not in line


class TestSolveTelemetry:
    def test_2d_mesh_solve_span_tree(self):
        """The ISSUE 4 acceptance pin: one telemetry'd solve on a
        2D-mesh engine yields pivot/permute/eliminate/residual child
        spans and DISTINCT compile/execute spans; its Chrome-trace
        export loads as valid trace-event JSON."""
        tel = Telemetry()
        r = solve(64, 16, workers=(2, 4), telemetry=tel)
        assert r.trace is not None and r.trace.name == "solve"
        names = {s.name for s in r.trace.walk()}
        assert {"compile", "execute", "pivot", "permute", "eliminate",
                "residual"} <= names
        ex = r.trace.find("execute")
        assert r.trace.find("compile") is not ex
        # The dedup satellite's contract: elapsed IS the execute span's
        # duration (one shared bracket — they cannot disagree).
        assert r.elapsed == ex.duration
        assert {c.name for c in ex.children} >= set(PHASES)
        assert all(c.attrs.get("modeled") for c in ex.children
                   if c.name in PHASES)
        text = json.dumps(export.to_chrome_trace(tel))
        assert check_telemetry.check_chrome_trace(text, "<test>") >= 6

    def test_no_telemetry_means_no_trace(self):
        r = solve(32, 16)
        assert r.trace is None

    def test_attribute_phases_measured_partitions_execute(self):
        from tpu_jordan.obs.spans import attribute_phases_measured

        tel = Telemetry(clock=FakeClock())
        with tel.span("execute") as ex:
            pass
        kids = attribute_phases_measured(
            ex, {"pivot": 0.5, "permute": 0.1, "eliminate": 0.4})
        assert [k.name for k in kids] == list(PHASES)
        assert kids[0].t_start == ex.t_start
        assert kids[-1].t_end == ex.t_end
        for a, b in zip(kids, kids[1:]):
            assert a.t_end == b.t_start
        for k in kids:
            assert k.attrs["measured"] is True
            assert k.attrs["source"] == "kernel_bracket"
            assert "modeled" not in k.attrs
        assert abs(sum(k.attrs["fraction"] for k in kids) - 1.0) < 1e-5

    def test_checker_rejects_modeled_phases_in_pallas_trace(self):
        """ISSUE 6 satellite: a fused-kernel engine's execute span with
        MODEL-attributed phase children is an attribution regression —
        tools/check_telemetry.py must fail the trace (and accept the
        measured form)."""
        from tpu_jordan.obs.spans import attribute_phases_measured

        tel = Telemetry(clock=FakeClock())
        with tel.span("solve"):
            with tel.span("execute", engine="grouped_pallas") as ex:
                pass
        attribute_phases(ex, 96, 16)             # the WRONG attribution
        bad = json.dumps(export.to_chrome_trace(tel))
        with pytest.raises(AssertionError, match="modeled phase child"):
            check_telemetry.check_chrome_trace(bad, "<test>")

        tel2 = Telemetry(clock=FakeClock())
        with tel2.span("solve"):
            with tel2.span("execute", engine="grouped_pallas") as ex2:
                pass
        attribute_phases_measured(
            ex2, {"pivot": 0.3, "permute": 0.2, "eliminate": 0.5})
        good = json.dumps(export.to_chrome_trace(tel2))
        assert check_telemetry.check_chrome_trace(good, "<test>") > 0
        # A pure-XLA engine's modeled children remain legal.
        tel3 = Telemetry(clock=FakeClock())
        with tel3.span("solve"):
            with tel3.span("execute", engine="inplace") as ex3:
                pass
        attribute_phases(ex3, 96, 16)
        xla = json.dumps(export.to_chrome_trace(tel3))
        assert check_telemetry.check_chrome_trace(xla, "<test>") > 0

    def test_auto_select_records_select_span(self):
        from tpu_jordan.tuning.tuner import auto_select

        tel = Telemetry(clock=FakeClock())
        engine, group, plan = auto_select(256, 64, "float32", 1, True,
                                          telemetry=tel)
        sp = tel.find("select")
        assert sp is not None and sp.attrs["engine"] == engine
        assert sp.attrs["source"] in ("cache", "cost_model", "measured")

    def test_tuner_plan_cache_hit_miss_metrics(self, tmp_path):
        from tpu_jordan.tuning.plan_cache import PlanCache
        from tpu_jordan.tuning.registry import TunePoint
        from tpu_jordan.tuning.tuner import Tuner

        hits = REGISTRY.counter("tpu_jordan_plan_cache_hits_total")
        misses = REGISTRY.counter("tpu_jordan_plan_cache_misses_total")
        cache = PlanCache(path=str(tmp_path / "plans.json"))
        t = Tuner(cache=cache)
        pt = TunePoint.create(256, 64, "float32", 1, True)
        h0, m0 = hits.total(), misses.total()
        t.select(pt)                 # cold -> miss, plan written back
        t.select(pt)                 # warm -> hit
        assert misses.total() == m0 + 1
        assert hits.total() == h0 + 1

    def test_scoreboard_timed_shim_is_span_backed(self):
        from tpu_jordan.utils.profiling import Scoreboard, timed

        tel = Telemetry(clock=FakeClock())
        with timed("glob", flops=2e9, telemetry=tel) as sb:
            pass
        assert sb.elapsed == 1.0
        assert sb.report() == "glob_time: 1.00  (2.0 GFLOP/s)"
        sp = tel.roots[0]
        assert sp.name == "glob" and sp.duration == sb.elapsed
        # Satellite: GFLOP/s rides the span as an attribute.
        assert sp.attrs["gflops"] == 2.0
        assert isinstance(Scoreboard("x"), Scoreboard)


def _scrape_compiles_total() -> float:
    """Sum every ``tpu_jordan_compiles_total`` series from an actual
    Prometheus-text scrape of the process registry (the acceptance pin
    reads the exported format, not a Python attribute)."""
    total = 0.0
    for line in export.to_prometheus(REGISTRY).splitlines():
        if line.startswith("tpu_jordan_compiles_total{") or \
                line.startswith("tpu_jordan_compiles_total "):
            total += float(line.rsplit(" ", 1)[1])
    return total


class TestServeTelemetry:
    @pytest.mark.smoke
    def test_warm_scrape_zero_compiles_across_50_requests(self):
        """ISSUE 4 acceptance: a warm JordanService Prometheus scrape
        reports ``tpu_jordan_compiles_total`` unchanged across 50
        requests (the smoke-tier serve round trip)."""
        from tpu_jordan.serve import JordanService

        tel = Telemetry()
        rng = np.random.default_rng(0)
        with JordanService(batch_cap=4, max_queue=128,
                           telemetry=tel) as svc:
            svc.warmup(shapes=[32])
            before = _scrape_compiles_total()
            futs = [svc.submit(
                2.0 * np.eye(32, dtype=np.float32)
                + 0.1 * rng.standard_normal((32, 32)).astype(np.float32))
                for _ in range(50)]
            results = [f.result(timeout=120) for f in futs]
            after = _scrape_compiles_total()
        assert after == before, "warm serve path must never compile"
        assert len(results) == 50
        assert not any(r.singular for r in results)
        # Zero-compile warm trace: the only compile span is warmup's.
        assert sum(1 for s in tel.spans() if s.name == "compile") == 1
        assert any(s.name == "execute" for s in tel.spans())

    def test_stats_rebase_preserves_snapshot_and_mirrors_registry(self):
        from tpu_jordan.serve.stats import ServeStats

        reqs = REGISTRY.counter("tpu_jordan_serve_requests_total")
        before = reqs.value(bucket="999")
        s = ServeStats()
        s.request(999)
        s.batch(999, occupancy=3, exec_seconds=0.5,
                queue_seconds=[0.1, 0.2, 0.3])
        snap = s.snapshot()["buckets"]["999"]
        # The ISSUE 3 snapshot contract, byte-for-byte keys.
        assert snap["requests"] == 1 and snap["batches"] == 1
        assert snap["mean_occupancy"] == 3.0
        assert snap["execute_ms"]["p50"] == 500.0
        assert snap["queue_ms"]["p95"] == 300.0
        # ...and the same mutation landed in the process registry.
        assert reqs.value(bucket="999") == before + 1


class TestCLI:
    def test_metrics_out_and_trace_json(self, tmp_path):
        from tpu_jordan.__main__ import main

        mpath = tmp_path / "metrics.prom"
        tpath = tmp_path / "trace.json"
        rc = main(["48", "16", "--quiet", "--metrics-out", str(mpath),
                   "--trace-json", str(tpath)])
        assert rc == 0
        assert check_telemetry.check_prometheus(
            mpath.read_text(), str(mpath)) > 0
        assert check_telemetry.check_chrome_trace(
            tpath.read_text(), str(tpath)) > 0
        # The checker CLI itself agrees (the metrics-demo target path).
        assert check_telemetry.main([str(mpath), str(tpath)]) == 0
