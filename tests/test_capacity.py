"""ISSUE 13 — the capacity observatory: the process-wide byte ledger
(created == live + evicted per metered class, high-water marks), the
resident-handle CapacityBudget (LRU eviction over last-served with
pinned exemption, typed CapacityExceededError at submit — never an OOM
mid-launch), budget eviction racing an in-flight update txn (the PR 11
STATE→STORE lock order extended to the budget evictor), lane byte
projection before any compile, the sticky device live-bytes watermark
(re-probed every snapshot on supporting backends, disabled forever on
a first probe that reported nothing — both behaviors pinned), and the
``check_capacity.py`` both-ways gate."""

import importlib.util
import pathlib
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.obs.capacity import (CapacityBudget, CapacityLedger,
                                     capacity_demo, lru_policy)
from tpu_jordan.resilience.policy import CapacityExceededError
from tpu_jordan.serve.handles import (HandleState, HandleStore,
                                      UnknownHandleError,
                                      resident_handle_bytes)

_repo = pathlib.Path(__file__).resolve().parent.parent
_spec = importlib.util.spec_from_file_location(
    "check_capacity", _repo / "tools" / "check_capacity.py")
check_capacity = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_capacity)


def _state(hid, bucket=64, n=4):
    return HandleState(handle_id=hid, n=n, bucket_n=bucket,
                       dtype="float32", a=np.eye(n), inverse=np.eye(n))


class TestLedger:
    def test_register_release_reconciles(self):
        led = CapacityLedger()
        led.register("handles", "a", 100, detail="n64")
        led.register("handles", "b", 50, detail="n64")
        assert led.live_bytes("handles") == 150
        led.release("handles", "a")
        snap = led.snapshot()["components"]["handles"]
        assert snap["bytes_created"] == 150
        assert snap["bytes_live"] == 50
        assert snap["bytes_evicted"] == 100
        assert snap["bytes_created"] == (snap["bytes_live"]
                                         + snap["bytes_evicted"])
        assert snap["high_water_bytes"] == 150
        assert snap["breakdown"] == {"n64": 50}

    def test_reregister_same_key_counts_old_as_evicted(self):
        """Replace semantics: a re-created key's old bytes are evicted,
        never silently lost — the reconciliation invariant survives
        re-inverts and plan-cache re-saves."""
        led = CapacityLedger()
        led.register("plan_cache", "k", 100)
        led.register("plan_cache", "k", 300)
        snap = led.snapshot()["components"]["plan_cache"]
        assert snap["bytes_live"] == 300
        assert snap["bytes_created"] == 400
        assert snap["bytes_evicted"] == 100
        assert snap["entries"] == 1

    def test_double_release_is_noop_never_negative(self):
        led = CapacityLedger()
        led.register("handles", "a", 10)
        assert led.release("handles", "a") == 10
        assert led.release("handles", "a") == 0
        assert led.live_bytes("handles") == 0

    def test_sampled_probe_available_and_absent(self):
        """A probe returning None reports available=False — absent,
        never zeroed; a probe raising is absent too (telemetry must
        never fail a snapshot)."""
        led = CapacityLedger()
        led.register_probe("ring", lambda: {"bytes": 42, "extra": 1})
        led.register_probe("dev", lambda: None)
        led.register_probe("boom", lambda: 1 / 0)
        comps = led.snapshot()["components"]
        assert comps["ring"] == {"kind": "sampled", "available": True,
                                 "bytes_live": 42, "extra": 1}
        assert comps["dev"] == {"kind": "sampled", "available": False}
        assert comps["boom"] == {"kind": "sampled", "available": False}

    def test_process_ledger_gauges_mirrored(self):
        from tpu_jordan.obs import capacity as cap
        from tpu_jordan.obs.metrics import REGISTRY

        key = ("test_capacity", "gauge-mirror")
        cap.register("handles", key, 7, detail="test")
        g = REGISTRY.gauge("tpu_jordan_capacity_bytes")
        assert g.value(component="handles") >= 7
        created = REGISTRY.counter(
            "tpu_jordan_capacity_bytes_created_total")
        assert created.value(component="handles") >= 7
        cap.release("handles", key)


class TestWatermark:
    """ISSUE 13 satellite: the PR 9 one-shot device watermark,
    re-based as a sticky first-probe decision."""

    def test_unsupported_first_probe_sticky_forever(self):
        from tpu_jordan.obs.hwcost import DeviceMemoryWatermark

        calls = []

        def sampler():
            calls.append(1)
            return None if len(calls) == 1 else {"bytes_in_use": 9}

        wm = DeviceMemoryWatermark(sampler=sampler)
        assert wm.sample() is None
        assert wm.available is False
        # The backend "starts reporting" later — irrelevant: the first
        # probe's verdict is final, the sampler is never called again.
        assert wm.sample() is None
        assert wm.sample() is None
        assert calls == [1]

    def test_supported_backend_reprobed_every_sample(self):
        from tpu_jordan.obs.hwcost import DeviceMemoryWatermark

        vals = iter([100, 200, 300])
        calls = []

        def sampler():
            v = next(vals)
            calls.append(v)
            return {"bytes_in_use": v, "peak_bytes_in_use": 300}

        wm = DeviceMemoryWatermark(sampler=sampler)
        assert wm.sample()["bytes_in_use"] == 100
        assert wm.available is True
        assert wm.sample()["bytes_in_use"] == 200
        assert wm.sample()["bytes_in_use"] == 300
        assert calls == [100, 200, 300]

    def test_transient_none_on_supported_backend_never_zeroes(self):
        """A supporting backend hiccuping one empty read must not
        disable the watermark (the old per-instance tri-state did) —
        and must not zero the gauges (absent is honest)."""
        from tpu_jordan.obs.hwcost import DeviceMemoryWatermark
        from tpu_jordan.obs.metrics import REGISTRY

        seq = iter([{"bytes_in_use": 77}, None, {"bytes_in_use": 88}])
        wm = DeviceMemoryWatermark(sampler=lambda: next(seq))
        assert wm.sample(probe="t")["bytes_in_use"] == 77
        g = REGISTRY.gauge("tpu_jordan_device_bytes_in_use")
        assert g.value(probe="t") == 77
        assert wm.sample(probe="t") is None       # transient miss
        assert wm.available is True               # ... not a verdict
        assert g.value(probe="t") == 77           # never zeroed
        assert wm.sample(probe="t")["bytes_in_use"] == 88
        assert g.value(probe="t") == 88

    def test_capacity_snapshot_reprobes_supported_backend(self,
                                                          monkeypatch):
        """The capacity snapshot's device component goes through the
        sticky probe — one sampler call per snapshot on a supporting
        backend."""
        from tpu_jordan.obs import capacity as cap
        from tpu_jordan.obs import hwcost
        from tpu_jordan.obs.hwcost import DeviceMemoryWatermark

        calls = []

        def sampler():
            calls.append(1)
            return {"bytes_in_use": 5, "peak_bytes_in_use": 6}

        monkeypatch.setattr(hwcost, "WATERMARK",
                            DeviceMemoryWatermark(sampler=sampler))
        d1 = cap.snapshot()["components"]["device"]
        d2 = cap.snapshot()["components"]["device"]
        assert d1 == {"kind": "sampled", "available": True,
                      "bytes_live": 5, "peak_bytes_in_use": 6}
        assert d2 == d1
        assert len(calls) == 2

    def test_cpu_backend_stays_unavailable_in_snapshot(self):
        """On this CPU host the real allocator reports nothing: the
        device component is available=False — never zeroed, never
        modeled (the pinned PR 9 behavior, now at every snapshot)."""
        from tpu_jordan.obs import capacity as cap

        dev = cap.snapshot()["components"]["device"]
        assert dev == {"kind": "sampled", "available": False}


class TestBudgetedHandleStore:
    def test_resident_handle_bytes_unit(self):
        assert resident_handle_bytes(64, jnp.float32) == 2 * 64 * 64 * 4
        assert resident_handle_bytes(128, jnp.float64) == 2 * 128**2 * 8

    @staticmethod
    def _commit_noop(store, hid):
        """One COMMITTED serve of a handle (the commit-gated LRU
        stamp: only a txn that wrote through refreshes the handle's
        eviction position)."""
        with store.txn(hid) as st:
            store.commit(st, a=st.a, inverse=st.inverse, kappa=1.0,
                         rel_residual=0.0, drift=0.0)

    def test_lru_eviction_order_and_pin_exemption(self):
        """The budget evicts the least-recently-SERVED unpinned handle:
        a COMMITTED txn refreshes the stamp, a pin exempts entirely."""
        per = resident_handle_bytes(64, jnp.float32)
        t = [0.0]

        def clock():
            t[0] += 1.0
            return t[0]

        store = HandleStore(budget=CapacityBudget(max_bytes=2 * per),
                            clock=clock)
        store.create(_state("h1"))
        store.create(_state("h2"))
        self._commit_noop(store, "h1")    # serve h1: h2 becomes LRU
        store.create(_state("h3"))        # must evict h2
        assert store.ids() == ["h1", "h3"]
        snap = store.budget_snapshot()
        assert snap["budget_evictions"] == 1
        assert snap["live_bytes"] == 2 * per
        # Pin the LRU handle: the NEXT admission must skip it and
        # evict the other.
        self._commit_noop(store, "h3")    # h1 is now LRU
        store.pin("h1")
        store.create(_state("h4"))
        assert store.ids() == ["h1", "h4"]

    def test_failed_txn_does_not_refresh_lru(self):
        """Review hardening: a txn that raises WITHOUT committing must
        not bump last_served — a handle whose updates keep failing
        typed cannot squat on residency by refreshing its own
        eviction position."""
        per = resident_handle_bytes(64, jnp.float32)
        t = [0.0]

        def clock():
            t[0] += 1.0
            return t[0]

        store = HandleStore(budget=CapacityBudget(max_bytes=2 * per),
                            clock=clock)
        store.create(_state("sick"))
        store.create(_state("healthy"))
        self._commit_noop(store, "healthy")
        with pytest.raises(RuntimeError):
            with store.txn("sick"):
                raise RuntimeError("gate exhausted, nothing committed")
        store.create(_state("h3"))    # must evict the SICK handle
        assert store.ids() == ["h3", "healthy"]

    def test_concurrent_distinct_creates_never_overshoot_budget(self):
        """Review hardening (admission atomic with install): racing
        creates of DISTINCT ids can both pass the eviction pass, but
        the install-time re-check under the store lock means live
        bytes never exceed the ceiling — the loser re-evicts or
        refuses typed, it never silently overshoots."""
        per = resident_handle_bytes(64, jnp.float32)
        store = HandleStore(budget=CapacityBudget(max_bytes=2 * per))
        store.create(_state("seed"))
        peak = []
        refused = []

        def creator(i):
            try:
                store.create(_state(f"d{i}"))
            except CapacityExceededError:
                refused.append(i)
            with store._lock:
                peak.append(store._live_bytes)

        threads = [threading.Thread(target=creator, args=(i,))
                   for i in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(30)
        assert not any(th.is_alive() for th in threads)
        assert max(peak) <= 2 * per
        assert store.budget_snapshot()["live_bytes"] <= 2 * per

    def test_same_id_recreate_credits_replaced_bytes(self):
        """Review hardening: a same-id re-create REPLACES — its old
        bytes are credited at admission, so a net-zero replacement
        under a full budget neither refuses nor evicts an innocent
        handle (and the ledger still reconciles: old bytes evicted by
        the replace, new bytes created)."""
        per = resident_handle_bytes(64, jnp.float32)
        store = HandleStore(budget=CapacityBudget(max_bytes=2 * per))
        store.create(_state("h1"))
        store.create(_state("h2"))
        store.create(_state("h1"))        # net-zero replacement
        assert store.ids() == ["h1", "h2"]
        snap = store.budget_snapshot()
        assert snap["budget_evictions"] == 0
        assert snap["refusals"] == 0
        assert snap["live_bytes"] == 2 * per
        # A single-handle budget replaces in place too.
        tight = HandleStore(budget=CapacityBudget(max_bytes=per))
        tight.create(_state("x"))
        tight.create(_state("x"))
        assert tight.ids() == ["x"]
        assert tight.budget_snapshot()["refusals"] == 0

    def test_all_pinned_admission_typed_refusal(self):
        per = resident_handle_bytes(64, jnp.float32)
        store = HandleStore(budget=CapacityBudget(max_bytes=2 * per))
        store.create(_state("h1"))
        store.create(_state("h2"))
        store.pin("h1")
        store.pin("h2")
        with pytest.raises(CapacityExceededError):
            store.create(_state("h3"))
        assert store.ids() == ["h1", "h2"]      # nothing installed
        assert store.budget_snapshot()["refusals"] == 1
        store.unpin("h2")
        store.create(_state("h3"))              # now h2 is evictable
        assert store.ids() == ["h1", "h3"]

    def test_eviction_events_recorded_with_cause(self):
        from tpu_jordan.obs.recorder import RECORDER

        per = resident_handle_bytes(64, jnp.float32)
        store = HandleStore(budget=CapacityBudget(max_bytes=per))
        mark = RECORDER.total
        store.create(_state("h1"))
        store.create(_state("h2"))              # budget-evicts h1
        store.evict("h2")                       # caller lifecycle
        evs = [e for e in RECORDER.since(mark)
               if e["kind"] == "capacity_eviction"]
        assert [(e["handle_id"], e["cause"]) for e in evs] == [
            ("h1", "budget"), ("h2", "caller")]
        assert evs[0]["budget_bytes"] == per
        assert evs[0]["nbytes"] == per

    def test_budget_evict_waits_out_inflight_update_txn(self):
        """ISSUE 13 satellite: the budget evictor inherits the PR 11
        STATE→STORE discipline — an admission that must evict a handle
        mid-txn WAITS for the commit and re-checks identity, so a
        committed update is never orphaned by the *budget* either."""
        per = resident_handle_bytes(64, jnp.float32)
        store = HandleStore(budget=CapacityBudget(max_bytes=per))
        store.create(_state("x"))
        entered = threading.Event()
        release = threading.Event()
        versions = []

        def updater():
            with store.txn("x") as live:
                entered.set()
                release.wait(10)
                store.commit(live, a=np.eye(4), inverse=np.eye(4),
                             kappa=1.0, rel_residual=0.0, drift=0.0)
                versions.append(live.version)

        t = threading.Thread(target=updater)
        t.start()
        assert entered.wait(10)
        admitted = []
        admitter = threading.Thread(
            target=lambda: admitted.extend(store.ensure_capacity(per)))
        admitter.start()
        time.sleep(0.05)
        assert admitter.is_alive()    # blocked on the txn, not racing
        release.set()
        t.join(10)
        admitter.join(10)
        assert versions == [1]        # the commit landed first ...
        assert admitted == ["x"]      # ... THEN the budget evicted it
        with pytest.raises(UnknownHandleError):
            store.get("x")

    def test_seeded_concurrent_updates_vs_budget_evictions(self):
        """Seeded stress: update txns racing budget admissions never
        deadlock, never orphan a commit — every commit that succeeded
        happened on the then-live state, every loser is the typed
        UnknownHandleError."""
        rng = np.random.default_rng(7)
        per = resident_handle_bytes(64, jnp.float32)
        store = HandleStore(budget=CapacityBudget(max_bytes=2 * per))
        store.create(_state("a"))
        store.create(_state("b"))
        outcomes = {"committed": 0, "typed": 0}
        lock = threading.Lock()
        order = rng.permutation(24)

        def worker(i):
            hid = "a" if order[i] % 2 else "b"
            try:
                with store.txn(hid) as st:
                    store.commit(st, a=st.a, inverse=st.inverse,
                                 kappa=1.0, rel_residual=0.0,
                                 drift=0.0)
                with lock:
                    outcomes["committed"] += 1
            except UnknownHandleError:
                with lock:
                    outcomes["typed"] += 1

        def evictor(i):
            try:
                store.ensure_capacity(per)
                store.create(_state("a" if order[i] % 2 else "b"))
            except CapacityExceededError:
                pass

        threads = ([threading.Thread(target=worker, args=(i,))
                    for i in range(16)]
                   + [threading.Thread(target=evictor, args=(i,))
                      for i in range(8)])
        for th in threads:
            th.start()
        for th in threads:
            th.join(30)
        assert not any(th.is_alive() for th in threads)
        assert outcomes["committed"] + outcomes["typed"] == 16
        snap = store.budget_snapshot()
        assert snap["live_bytes"] <= 2 * per


class TestServeAdmission:
    @pytest.fixture(scope="class")
    def warm_service(self):
        """One warmed budgeted service per class (the compiles are the
        expensive part); each test OWNS its handles — created under its
        own ids and evicted on the way out — so every test passes in
        isolation and in any order (review hardening)."""
        from tpu_jordan.serve.service import JordanService

        per = resident_handle_bytes(64, jnp.float32)
        svc = JordanService(engine="auto", batch_cap=1, max_wait_ms=0.5,
                            handle_budget_bytes=2 * per)
        svc.warmup(update_shapes=[(48, 8)])
        yield svc, per
        svc.close()

    @pytest.fixture
    def budgeted_service(self, warm_service):
        svc, per = warm_service
        yield svc, per
        for hid in svc.handles.ids():     # leave the store empty
            svc.handles.unpin(hid)
            svc.handles.evict(hid)

    @pytest.mark.smoke    # the capacity round-trip (ISSUE 13 smoke)
    def test_budgeted_resident_round_trip_warm_pins(self,
                                                    budgeted_service,
                                                    rng):
        """The smoke-tier capacity round trip: with metering and a
        budget ON, a resident create + update + budget eviction +
        typed refusal runs with ZERO compiles and ZERO plan-cache
        measurements after warmup — the observatory costs the warm
        path nothing."""
        from tpu_jordan.obs.metrics import REGISTRY

        svc, per = budgeted_service
        compiles = REGISTRY.counter("tpu_jordan_compiles_total")
        meas = REGISTRY.counter("tpu_jordan_tuner_measurements_total")
        c0, m0 = compiles.total(), meas.total()
        a1 = rng.standard_normal((48, 48)).astype(np.float32)
        a2 = rng.standard_normal((48, 48)).astype(np.float32)
        a3 = rng.standard_normal((48, 48)).astype(np.float32)
        r1 = svc.invert(a1, resident=True, handle_id="c1", timeout=600)
        svc.invert(a2, resident=True, handle_id="c2", timeout=600)
        u = rng.standard_normal((48, 4)).astype(np.float32) * 0.01
        v = rng.standard_normal((48, 4)).astype(np.float32) * 0.01
        res = svc.update(r1, u, v, timeout=600)
        assert res.update_outcome == "refreshed"
        # Budget full: the third create evicts the LRU (c2 — c1 was
        # just served).
        svc.invert(a3, resident=True, handle_id="c3", timeout=600)
        assert svc.handles.ids() == ["c1", "c3"]
        svc.handles.pin("c1")
        svc.handles.pin("c3")
        with pytest.raises(CapacityExceededError):
            svc.invert(a2, resident=True, handle_id="c4", timeout=600)
        svc.handles.unpin("c1")
        svc.handles.unpin("c3")
        assert compiles.total() - c0 == 0
        assert meas.total() - m0 == 0
        snap = svc.stats()
        assert snap["handle_budget"]["max_bytes"] == 2 * per
        assert snap["handle_budget"]["budget_evictions"] >= 1
        assert snap["handle_budget"]["refusals"] >= 1

    def test_refused_invert_never_submitted(self, budgeted_service,
                                            rng):
        """The typed refusal happens AT SUBMIT: the invert never enters
        the queue, the request counter does not move, and the journey
        closes with the typed error (no gap)."""
        from tpu_jordan.obs.metrics import REGISTRY

        svc, per = budgeted_service
        for hid in ("r1", "r2"):
            a = rng.standard_normal((48, 48)).astype(np.float32)
            svc.invert(a, resident=True, handle_id=hid, timeout=600)
            svc.handles.pin(hid)
        req = REGISTRY.counter("tpu_jordan_serve_requests_total")
        r0 = req.total()
        a = rng.standard_normal((48, 48)).astype(np.float32)
        with pytest.raises(CapacityExceededError):
            svc.invert(a, resident=True, handle_id="r3", timeout=600)
        assert req.total() == r0
        ctx = svc.journey.contexts()[-1]
        assert ctx.outcome() == ("error", "CapacityExceededError")

    def test_budget_eviction_emits_journey_hop(self, budgeted_service,
                                               rng):
        """An admission-forced eviction is attributable to the request
        that forced it: a capacity_evict hop on ITS journey, mirrored
        into the flight recorder."""
        from tpu_jordan.obs.recorder import RECORDER

        svc, per = budgeted_service
        for hid in ("j1", "j2"):          # fill the 2-handle budget
            a = rng.standard_normal((48, 48)).astype(np.float32)
            svc.invert(a, resident=True, handle_id=hid, timeout=600)
        mark = RECORDER.total
        a = rng.standard_normal((48, 48)).astype(np.float32)
        svc.invert(a, resident=True, handle_id="j3", timeout=600)
        hops = [e for e in RECORDER.since(mark)
                if e["kind"] == "journey"
                and e.get("event") == "capacity_evict"]
        assert len(hops) == 1
        assert hops[0]["cause"] == "budget"
        assert hops[0]["handle"] == "j1"
        evs = [e for e in RECORDER.since(mark)
               if e["kind"] == "capacity_eviction"]
        assert len(evs) == 1 and evs[0]["cause"] == "budget"

    def test_project_capacity_before_any_compile(self):
        """Lane bytes are projectable WITHOUT compiling: a fresh
        service projects its whole update warmup set with the compile
        counter untouched, and the projection gauge carries each
        lane."""
        from tpu_jordan.obs.metrics import REGISTRY
        from tpu_jordan.serve.service import JordanService

        compiles = REGISTRY.counter("tpu_jordan_compiles_total")
        c0 = compiles.total()
        with JordanService(engine="auto", batch_cap=4,
                           max_wait_ms=0.5, autostart=False) as svc:
            proj = svc.project_capacity(update_shapes=[(48, 8)])
        assert compiles.total() == c0
        assert set(proj) == {"invert:64:b4", "invert:64:b1",
                             "update:64:b1:k8", "update:64:b4:k8"}
        assert all(v > 0 for v in proj.values())
        g = REGISTRY.gauge("tpu_jordan_capacity_projected_lane_bytes")
        assert g.value(lane="update:64:b1:k8") == proj["update:64:b1:k8"]

    def test_executor_lane_metered_at_compile(self, rng):
        """A compiled lane lands in the executor_lanes ledger with its
        memory_analysis footprint (this CPU backend reports it) — and
        the projection is its arg/out floor."""
        from tpu_jordan.obs import capacity as cap
        from tpu_jordan.serve.executors import projected_lane_bytes
        from tpu_jordan.serve.service import JordanService

        before = cap.live_bytes("executor_lanes")
        with JordanService(engine="auto", batch_cap=2,
                           max_wait_ms=0.5, autostart=False) as svc:
            svc.warmup(shapes=[48])
            ex = svc.executors.get(64, 2, svc._batcher.block_size)
        grown = cap.live_bytes("executor_lanes") - before
        assert grown > 0
        if ex.cost.available and ex.cost.hbm_bytes is not None:
            assert grown >= ex.cost.hbm_bytes > 0
            assert (projected_lane_bytes(64, 2, "float32")
                    <= ex.cost.hbm_bytes)
        comps = cap.snapshot()["components"]["executor_lanes"]
        assert comps["bytes_created"] == (comps["bytes_live"]
                                          + comps["bytes_evicted"])

    def test_shared_store_plus_budget_param_typed(self):
        from tpu_jordan.driver import UsageError
        from tpu_jordan.serve.service import JordanService

        with pytest.raises(UsageError):
            JordanService(shared_handles=HandleStore(),
                          handle_budget_bytes=1024, autostart=False)


class TestFleetCapacity:
    def test_fleet_rollup_and_budgeted_store(self, rng):
        """The fleet-level rollup (ISSUE 13): stats()['capacity']
        carries every byte class with the reconciliation invariant,
        and handle_budget_bytes attaches ONE fleet-wide budget."""
        from tpu_jordan.fleet import JordanFleet

        from tpu_jordan.obs.recorder import RECORDER

        per = resident_handle_bytes(64, jnp.float32)
        with JordanFleet(replicas=2, batch_cap=1, max_wait_ms=0.5,
                         handle_budget_bytes=2 * per,
                         autostart_supervisor=False) as fleet:
            fleet.warmup([16])
            for hid in ("f0", "f1"):
                a = rng.standard_normal((16, 16)).astype(np.float32)
                fleet.invert(a, resident=True, handle_id=hid,
                             timeout=600)
            # The budget is full: the next fleet resident invert
            # evicts the LRU handle WITH a capacity_evict hop on the
            # admitting request's own fleet journey (review
            # hardening: fleet evictions are attributable too).
            mark = RECORDER.total
            a = rng.standard_normal((16, 16)).astype(np.float32)
            fleet.invert(a, resident=True, handle_id="f2", timeout=600)
            hops = [e for e in RECORDER.since(mark)
                    if e["kind"] == "journey"
                    and e.get("event") == "capacity_evict"]
            assert len(hops) == 1 and hops[0]["handle"] == "f0"
            assert hops[0]["request_id"].startswith("fleet")
            stats = fleet.stats()
        cap = stats["capacity"]["components"]
        for name in ("handles", "executor_lanes", "flight_recorder",
                     "device"):
            assert name in cap
        for doc in cap.values():
            if doc["kind"] == "metered":
                assert doc["bytes_created"] == (doc["bytes_live"]
                                                + doc["bytes_evicted"])
        assert stats["handle_budget"]["max_bytes"] == 2 * per
        assert stats["handles"]["f1"]["nbytes"] == per

    def test_fleet_store_and_budget_param_typed(self):
        from tpu_jordan.driver import UsageError
        from tpu_jordan.fleet import JordanFleet

        with pytest.raises(UsageError):
            JordanFleet(replicas=2, handle_store=HandleStore(),
                        handle_budget_bytes=1024,
                        autostart_supervisor=False)


class TestDemoAndChecker:
    @pytest.fixture(scope="class")
    def demo_report(self):
        return capacity_demo(n=48, budget_handles=2)

    def test_demo_report_valid(self, demo_report):
        errs, silent = check_capacity.check(demo_report)
        assert errs == [] and silent == [], (errs, silent)
        assert demo_report["budget_evictions"] == 1
        assert demo_report["typed_overflow"]["raised"]
        assert demo_report["compiles_on_capacity_path"] == 0

    def test_doctored_reports_exit_2(self, demo_report, tmp_path):
        """Both-ways gate: doctored unmetered residency, a stripped
        eviction event, and a silent stale serve each exit 2; a
        missing typed overflow is a bound violation (exit 1)."""
        import copy
        import json

        def rc(rep, name):
            p = tmp_path / name
            p.write_text(json.dumps(rep))
            return check_capacity.main([str(p)])

        assert rc(demo_report, "ok.json") == 0
        # Unmetered residency: live bytes nothing created.
        d1 = copy.deepcopy(demo_report)
        d1["ledger"]["components"]["handles"]["bytes_live"] += 4096
        assert rc(d1, "unmetered.json") == 2
        # A budget eviction with no recorded event.
        d2 = copy.deepcopy(demo_report)
        d2["evictions"] = []
        assert rc(d2, "silent_evict.json") == 2
        # An eviction event missing its budget context.
        d3 = copy.deepcopy(demo_report)
        del d3["evictions"][0]["budget_bytes"]
        assert rc(d3, "unexplained.json") == 2
        # A whole byte class vanishing from the ledger.
        d4 = copy.deepcopy(demo_report)
        del d4["ledger"]["components"]["executor_lanes"]
        assert rc(d4, "no_lanes.json") == 2
        # Update-after-evict not typed = a silently stale serve.
        d5 = copy.deepcopy(demo_report)
        d5["update_after_evict_typed"] = None
        assert rc(d5, "stale_serve.json") == 2
        # Typed overflow missing: a bound violation, not silence.
        d6 = copy.deepcopy(demo_report)
        d6["typed_overflow"] = {"raised": False, "error": None,
                                "refusals": 0}
        assert rc(d6, "overflow.json") == 1
        # A compile on the warm capacity path: bound violation.
        d7 = copy.deepcopy(demo_report)
        d7["compiles_on_capacity_path"] = 1
        assert rc(d7, "compile.json") == 1

    def test_cli_flag_contract_exit_1(self):
        from tpu_jordan.__main__ import main

        assert main(["96", "32", "--capacity-demo", "--fleet-demo",
                     "--quiet"]) == 1
        assert main(["96", "32", "--capacity-demo", "--workers", "8",
                     "--quiet"]) == 1
        assert main(["96", "32", "--capacity-demo", "--workload",
                     "solve", "--quiet"]) == 1
        assert main(["96", "32", "--capacity-demo", "--numerics",
                     "summary", "--quiet"]) == 1
        assert main(["96", "32", "--capacity-demo", "--batch-cap", "4",
                     "--quiet"]) == 1
        assert main(["96", "32", "--capacity-demo", "--replicas", "2",
                     "--quiet"]) == 1
        assert main(["96", "32", "--capacity-demo", "--plan-cache",
                     "/tmp/p.json", "--quiet"]) == 1
        assert main(["96", "32", "--capacity-demo", "--slo-report",
                     "--quiet"]) == 1

    def test_capacity_report_flag_writes_snapshot(self, tmp_path):
        import json

        from tpu_jordan.__main__ import main

        out = tmp_path / "cap.json"
        assert main(["16", "8", "--quiet",
                     "--capacity-report", str(out)]) == 0
        doc = json.loads(out.read_text())
        assert "components" in doc
        assert doc["components"]["device"]["available"] is False
