"""Unit tests for the row-block-cyclic layout math.

Parity oracle: the reference's closed forms (rows_p_process main.cpp:95-116,
local_to_global main.cpp:118-123, find_sender main.cpp:521-532), re-derived
here independently by brute force over the cyclic assignment rule
"block r -> worker r % p".
"""

import numpy as np
import pytest

from tpu_jordan.parallel import layout


@pytest.mark.parametrize("n,m", [(1, 1), (7, 3), (12, 4), (100, 7), (1024, 48)])
def test_num_block_rows(n, m):
    assert layout.num_block_rows(n, m) == int(np.ceil(n / m))


@pytest.mark.parametrize("Nr", [1, 2, 5, 8, 17])
@pytest.mark.parametrize("p", [1, 2, 3, 8])
def test_rows_per_worker_bruteforce(Nr, p):
    for k in range(p):
        expect = sum(1 for r in range(Nr) if r % p == k)
        assert layout.rows_per_worker(Nr, p, k) == expect
    assert sum(layout.rows_per_worker(Nr, p, k) for k in range(p)) == Nr


@pytest.mark.parametrize("m,p", [(3, 1), (3, 2), (4, 3), (5, 8)])
def test_local_to_global_roundtrip(m, p):
    # every (worker, local row) maps to a distinct global row whose owner is
    # that worker, matching gi = ((i/m)*p + k)*m + i%m (main.cpp:118-123)
    seen = set()
    for k in range(p):
        for i in range(4 * m):  # 4 local blocks
            gi = layout.local_to_global(i, m, p, k)
            assert layout.global_block_owner(gi // m, p) == k
            assert layout.global_to_local_block(gi // m, p) == i // m
            assert gi % m == i % m
            seen.add(gi)
    assert len(seen) == p * 4 * m


@pytest.mark.parametrize("Nr,p", [(1, 1), (5, 2), (8, 3), (3, 8), (16, 8)])
def test_find_sender_owns_last_block(Nr, p):
    s = layout.find_sender(Nr, p)
    assert s == (Nr - 1) % p
    assert layout.global_block_owner(Nr - 1, p) == s


def test_last_block_height():
    assert layout.last_block_height(10, 3) == 1
    assert layout.last_block_height(9, 3) == 3
    assert layout.last_block_height(1024, 48) == 1024 - 48 * 21


@pytest.mark.parametrize("n,m,p", [(10, 3, 4), (8, 4, 2), (7, 7, 8)])
def test_padded_num_blocks(n, m, p):
    Nr = layout.padded_num_blocks(n, m, p)
    assert Nr % p == 0
    assert Nr >= layout.num_block_rows(n, m)
    assert Nr - p < layout.num_block_rows(n, m) + p  # minimal


@pytest.mark.smoke          # the layout index-math case
def test_cyclic_layout_perms():
    lo = layout.CyclicLayout.create(n=10, m=3, p=2)
    assert lo.Nr == 4 and lo.N == 12
    order = lo.cyclic_block_order()
    # worker 0 stores blocks [0, 2], worker 1 stores [1, 3]
    assert order == [0, 2, 1, 3]
    g = np.asarray(layout.cyclic_gather_perm(lo))
    s = np.asarray(layout.cyclic_scatter_perm(lo))
    assert list(g) == order
    # scatter inverts gather
    x = np.arange(lo.Nr)
    assert (x[g][s] == x).all()
