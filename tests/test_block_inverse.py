import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.ops import (
    batched_block_inverse,
    gauss_jordan_inverse,
    generate,
    inf_norm,
)


def test_inverse_matches_numpy(rng):
    a = jnp.asarray(rng.standard_normal((16, 16)))
    inv, sing = gauss_jordan_inverse(a)
    assert not bool(sing)
    np.testing.assert_allclose(np.asarray(inv), np.linalg.inv(np.asarray(a)),
                               rtol=1e-10, atol=1e-10)


def test_zero_diagonal_requires_pivoting():
    # |i-j| blocks have zero diagonals; partial pivoting must handle them
    a = generate("absdiff", (8, 8), jnp.float64)
    inv, sing = gauss_jordan_inverse(a)
    assert not bool(sing)
    np.testing.assert_allclose(np.asarray(a @ inv), np.eye(8), atol=1e-10)


def test_singular_flagged():
    a = jnp.ones((4, 4), dtype=jnp.float64)  # rank 1
    _, sing = gauss_jordan_inverse(a)
    assert bool(sing)


def test_zero_matrix_flagged():
    # |norm| < eps path (main.cpp:782 second clause)
    _, sing = gauss_jordan_inverse(jnp.zeros((4, 4), dtype=jnp.float64))
    assert bool(sing)


def test_relative_threshold_uses_external_scale():
    # a well-conditioned small block must flag singular when judged against a
    # huge strip norm — parity with inverse_block(E, F, norm_a, ...) where
    # norm_a is the whole strip's norm (main.cpp:972,1046)
    a = jnp.eye(4, dtype=jnp.float64) * 1e-3
    _, sing_local = gauss_jordan_inverse(a)          # own norm: fine
    assert not bool(sing_local)
    _, sing_scaled = gauss_jordan_inverse(a, scale_norm=1e14)
    assert bool(sing_scaled)


@pytest.mark.parametrize("dtype,rtol", [(jnp.float64, 1e-9), (jnp.float32, 1e-3)])
def test_batched_matches_loop(rng, dtype, rtol):
    # keep blocks well-conditioned so the fp32 tolerance is meaningful
    blocks = jnp.asarray(
        rng.standard_normal((6, 8, 8)) + 4 * np.eye(8), dtype=dtype
    )
    invs, sings = batched_block_inverse(blocks)
    assert invs.shape == (6, 8, 8)
    assert not bool(sings.any())
    for b in range(6):
        np.testing.assert_allclose(
            np.asarray(blocks[b] @ invs[b]), np.eye(8), atol=rtol
        )


def test_batched_mixed_singular(rng):
    good = rng.standard_normal((8, 8))
    bad = np.ones((8, 8))
    blocks = jnp.asarray(np.stack([good, bad, good]))
    invs, sings = batched_block_inverse(blocks)
    assert list(np.asarray(sings)) == [False, True, False]
    np.testing.assert_allclose(
        np.asarray(blocks[0] @ invs[0]), np.eye(8), atol=1e-9
    )


def test_hilbert_conditioning_matches_reference_scale():
    # Reference golden behavior (SURVEY.md §4): Hilbert inverts for n<=8 at
    # EPS=1e-15 and hits the relative-threshold singularity cliff soon
    # after (n>=10 for the reference's op ordering; XLA's FMA fusion gives
    # slightly larger pivots, so ours crosses at n=13 — same rule, see
    # tests/test_jordan.py::TestHilbertGoldens).
    for n, ok in [(4, True), (8, True), (13, False)]:
        h = generate("hilbert", (n, n), jnp.float64)
        _, sing = gauss_jordan_inverse(h, eps=1e-15)
        assert bool(sing) == (not ok), f"n={n}"


def test_inf_norm():
    a = jnp.asarray([[1.0, -2.0], [3.0, 4.0]])
    assert float(inf_norm(a)) == 7.0


def test_condition_inf():
    from tpu_jordan.ops import condition_inf

    # Exact: κ∞(diag(1, 4)) = ‖A‖∞ · ‖A⁻¹‖∞ = 4 · 1 = 4.
    a = jnp.diag(jnp.asarray([1.0, 4.0]))
    assert float(condition_inf(a, jnp.diag(jnp.asarray([1.0, 0.25])))) == 4.0
    # And it matches numpy's ∞-norm condition number on a dense matrix.
    rng = np.random.default_rng(7)
    b = jnp.asarray(rng.standard_normal((32, 32)))
    got = float(condition_inf(b, jnp.asarray(np.linalg.inv(b))))
    want = np.linalg.cond(np.asarray(b), np.inf)
    np.testing.assert_allclose(got, want, rtol=1e-10)
