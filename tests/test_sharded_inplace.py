"""Distributed in-place (2N³) elimination: parity with the single-device
in-place engine and with the augmented distributed path, on the 8-device
virtual CPU mesh (VERDICT r2 item #1)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.ops import block_jordan_invert_inplace, generate
from tpu_jordan.parallel import distributed_residual, make_mesh
from tpu_jordan.parallel.sharded_inplace import (
    sharded_jordan_invert_inplace,
)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh(4)


class TestShardedInplace:
    @pytest.mark.parametrize("n,m", [
        (64, 8),
        # tier-1 budget: the (64, 8) config keeps the fast-run pin.
        pytest.param(128, 16, marks=pytest.mark.slow),
        pytest.param(100, 8, marks=pytest.mark.slow)])
    def test_matches_linalg_inv(self, rng, mesh8, n, m):
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv, sing = sharded_jordan_invert_inplace(a, mesh8, m)
        assert not bool(sing)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(np.asarray(a)), rtol=1e-7,
            atol=1e-7,
        )

    @pytest.mark.parametrize("p", [
        pytest.param(4, marks=pytest.mark.slow), 8])
    def test_matches_single_device_inplace(self, rng, p):
        # Same pivot rule end to end: the distributed in-place result must
        # agree with the single-chip in-place engine to rounding.
        mesh = make_mesh(p)
        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        inv_d, s_d = sharded_jordan_invert_inplace(a, mesh, 8)
        inv_s, s_s = block_jordan_invert_inplace(a, block_size=8)
        assert bool(s_d) == bool(s_s) is False
        np.testing.assert_allclose(
            np.asarray(inv_d), np.asarray(inv_s), rtol=1e-9, atol=1e-9
        )

    @pytest.mark.smoke      # the 1D-layout engine-parity case (ties incl.)
    def test_tied_pivots_match_single_device(self, mesh4):
        # |i-j| has exactly-repeated candidate blocks: ties must resolve to
        # the lowest global block row, matching the single-device argmin.
        # n=48 keeps the cyclic wrap (6 blocks over 4 workers) at half
        # the unrolled-trace cost of the old 96 (smoke budget).
        a = generate("absdiff", (48, 48), jnp.float64)
        inv_d, s_d = sharded_jordan_invert_inplace(a, mesh4, 8)
        inv_s, s_s = block_jordan_invert_inplace(a, block_size=8)
        assert bool(s_d) == bool(s_s) is False
        np.testing.assert_allclose(
            np.asarray(inv_d), np.asarray(inv_s), rtol=1e-9, atol=1e-12
        )

    @pytest.mark.slow   # tier-1 headroom (ISSUE 3): inplace-vs-augmented
    #   parity stays tier-1 at single-device (smoke); the distributed
    #   cross-engine leg runs nightly
    def test_matches_augmented_distributed(self, rng, mesh8):
        from tpu_jordan.parallel import sharded_jordan_invert

        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float64)
        inv_i, s_i = sharded_jordan_invert_inplace(a, mesh8, 8)
        inv_a, s_a = sharded_jordan_invert(a, mesh8, 8)
        assert bool(s_i) == bool(s_a) is False
        np.testing.assert_allclose(
            np.asarray(inv_i), np.asarray(inv_a), rtol=1e-9, atol=1e-9
        )

    def test_absdiff_residual(self, mesh8):
        a = generate("absdiff", (128, 128), jnp.float64)
        inv, sing = sharded_jordan_invert_inplace(a, mesh8, 16)
        assert not bool(sing)
        res = float(distributed_residual(a, inv, mesh8, 16))
        rel = res / float(jnp.max(jnp.sum(jnp.abs(a), axis=1)))
        assert rel < 1e-11

    def test_singular_collective_agreement(self, mesh8):
        a = jnp.ones((64, 64), jnp.float64)
        _, sing = sharded_jordan_invert_inplace(a, mesh8, 8)
        assert bool(sing)

    def test_sub_fp32_upcast_policy(self, rng, mesh4):
        a = jnp.asarray(rng.standard_normal((32, 32)), jnp.bfloat16)
        inv, sing = sharded_jordan_invert_inplace(a, mesh4, 8)
        assert inv.dtype == jnp.bfloat16
        assert not bool(sing)

    @pytest.mark.parametrize("n,m", [
        (128, 16),
        # tier-1 budget: the (128, 16) config keeps the fast-run pin.
        pytest.param(256, 32, marks=pytest.mark.slow),
        pytest.param(100, 8, marks=pytest.mark.slow)])
    def test_fori_bitmatches_unrolled(self, rng, mesh8, n, m):
        # The fori_loop engine (traced offsets, full-window masked probe)
        # must make the same pivot choices and produce bit-identical
        # results to the unrolled trace.
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
        x_u, s_u = sharded_jordan_invert_inplace(a, mesh8, m, unroll=True)
        x_f, s_f = sharded_jordan_invert_inplace(a, mesh8, m, unroll=False)
        assert bool(s_u) == bool(s_f)
        assert bool(jnp.all(x_u == x_f)), "1D fori engine diverged bitwise"

    def test_beyond_unroll_cap(self, rng, mesh4):
        # Nr = 68 > MAX_UNROLL_NR: the round-3 ceiling — used to raise
        # ValueError, now runs through the fori engine.
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n, m = 544, 8
        assert -(-n // m) > MAX_UNROLL_NR
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv, sing = sharded_jordan_invert_inplace(a, mesh4, m)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(inv) - np.eye(n)))
        assert res < 1e-7


class TestShardedGrouped:
    """The distributed delayed-group-update engine (VERDICT r4 #1): same
    pivot rule as every other engine, one fat trailing matmul + one
    stacked row psum per step; parity with the plain engines is to
    rounding (the grouped summation-order trade), and the grouped
    unrolled/fori pair is bit-identical."""

    @pytest.mark.parametrize("n,m,k", [
        (64, 8, 2),
        # tier-1 budget: the (64, 8, 2) config keeps the fast-run pin.
        pytest.param(128, 16, 4, marks=pytest.mark.slow),
        pytest.param(100, 8, 4, marks=pytest.mark.slow),
        pytest.param(96, 8, 3, marks=pytest.mark.slow)])
    def test_grouped_matches_plain_to_rounding(self, rng, mesh8, n, m, k):
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        x_p, s_p = sharded_jordan_invert_inplace(a, mesh8, m)
        x_g, s_g = sharded_jordan_invert_inplace(a, mesh8, m, group=k)
        assert bool(s_p) == bool(s_g) is False
        np.testing.assert_allclose(np.asarray(x_g), np.asarray(x_p),
                                   rtol=1e-9, atol=1e-9)

    @pytest.mark.slow  # tier-1 budget: grouped singular/beyond-cap/fori siblings stay
    def test_grouped_matches_single_chip_grouped(self, rng, mesh4):
        # Same grouped algorithm on both layouts -> rounding-level
        # agreement with the single-chip delayed-group-update engine.
        from tpu_jordan.ops import block_jordan_invert_inplace_grouped

        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        x_d, s_d = sharded_jordan_invert_inplace(a, mesh4, 8, group=2)
        x_s, s_s = block_jordan_invert_inplace_grouped(a, block_size=8,
                                                       group=2)
        assert bool(s_d) == bool(s_s) is False
        np.testing.assert_allclose(np.asarray(x_d), np.asarray(x_s),
                                   rtol=1e-9, atol=1e-9)

    @pytest.mark.parametrize("n,m,k", [
        (128, 16, 2),
        pytest.param(160, 8, 4, marks=pytest.mark.slow),
        pytest.param(100, 8, 4, marks=pytest.mark.slow)])
    def test_grouped_fori_bitmatches_unrolled(self, rng, mesh8, n, m, k):
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
        x_u, s_u = sharded_jordan_invert_inplace(a, mesh8, m, group=k,
                                                 unroll=True)
        x_f, s_f = sharded_jordan_invert_inplace(a, mesh8, m, group=k,
                                                 unroll=False)
        assert bool(s_u) == bool(s_f)
        assert bool(jnp.all(x_u == x_f)), "grouped fori diverged bitwise"

    @pytest.mark.slow
    def test_grouped_tied_pivots(self, mesh4):
        # |i-j|: repeated candidate blocks + zero diagonal — tie-breaks
        # and cross-group swaps must match the single-chip grouped engine.
        from tpu_jordan.ops import block_jordan_invert_inplace_grouped

        a = generate("absdiff", (96, 96), jnp.float64)
        x_d, s_d = sharded_jordan_invert_inplace(a, mesh4, 8, group=4)
        x_s, s_s = block_jordan_invert_inplace_grouped(a, block_size=8,
                                                       group=4)
        assert bool(s_d) == bool(s_s) is False
        np.testing.assert_allclose(np.asarray(x_d), np.asarray(x_s),
                                   rtol=1e-9, atol=1e-12)

    def test_grouped_singular_collective_agreement(self, mesh8):
        x_u, s_u = sharded_jordan_invert_inplace(
            jnp.ones((64, 64), jnp.float64), mesh8, 8, group=4)
        assert bool(s_u)
        _, s_f = sharded_jordan_invert_inplace(
            jnp.ones((64, 64), jnp.float64), mesh8, 8, group=4,
            unroll=False)
        assert bool(s_f)

    def test_grouped_beyond_unroll_cap(self, rng, mesh4):
        # Nr = 68 > MAX_UNROLL_NR routes to the grouped fori engine.
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n, m = 544, 8
        assert -(-n // m) > MAX_UNROLL_NR
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv, sing = sharded_jordan_invert_inplace(a, mesh4, m, group=4)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(inv) - np.eye(n)))
        assert res < 1e-7


class TestSwapFree:
    """The swap-free (implicit-permutation) 1D engine: half the per-step
    collective row bytes, one point-to-point row permutation at the end
    — bit-identical to the swap engines, ties included (the pivot tie
    rule keys on the swap COORDINATE, reproducing main.cpp:1051-1064)."""

    @pytest.mark.parametrize("n,m,p", [
        (64, 8, 4), (128, 16, 8),
        # tier-1 headroom (ISSUE 3): the ragged swap-free case runs
        # nightly; tier-1 keeps two 1D configs + the 2D swap-free pin.
        pytest.param(100, 8, 8, marks=pytest.mark.slow),
        pytest.param(96, 8, 4, marks=pytest.mark.slow)])
    def test_bitmatches_swap_engine(self, rng, n, m, p):
        mesh = make_mesh(p)
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        x_sf, s_sf = sharded_jordan_invert_inplace(a, mesh, m,
                                                   swapfree=True)
        x_sw, s_sw = sharded_jordan_invert_inplace(a, mesh, m)
        assert bool(s_sf) == bool(s_sw) is False
        assert bool(jnp.all(x_sf == x_sw)), "swap-free engine diverged"

    @pytest.mark.slow  # tier-1 budget: the 2D swap-free tied-pivot twin in
    # test_jordan2d_inplace keeps the fast-run deferred-permute tie pin
    def test_tied_pivots_bitmatch(self, mesh4):
        # |i-j|: exact ties + repeated swaps — the swap-coordinate tie
        # rule must reproduce the swap engines' choices exactly.
        a = generate("absdiff", (96, 96), jnp.float64)
        x_sf, s_sf = sharded_jordan_invert_inplace(a, mesh4, 8,
                                                   swapfree=True)
        x_sw, s_sw = sharded_jordan_invert_inplace(a, mesh4, 8)
        assert bool(s_sf) == bool(s_sw) is False
        assert bool(jnp.all(x_sf == x_sw))

    def test_singular_collective_agreement(self, mesh8):
        _, sing = sharded_jordan_invert_inplace(
            jnp.ones((64, 64), jnp.float64), mesh8, 8, swapfree=True)
        assert bool(sing)

    def test_all_singular_flags_agree_but_arrays_diverge(self, mesh4):
        # The engines' bit-match contract is scoped to NONSINGULAR
        # inputs: on an all-singular input both engines flag singular
        # (the only contractual output then), but their benign pin
        # targets differ — the swap engine self-swaps position t, the
        # swap-free engine pins the physical row at swap position t —
        # so the (invalid) arrays diverge bitwise.  Pin both facts so
        # the docstring scoping stays honest (ADVICE r5).
        ones = jnp.ones((64, 64), jnp.float64)
        x_sf, s_sf = sharded_jordan_invert_inplace(ones, mesh4, 8,
                                                   swapfree=True)
        x_sw, s_sw = sharded_jordan_invert_inplace(ones, mesh4, 8)
        assert bool(s_sf) and bool(s_sw)
        assert not bool(jnp.all(x_sf == x_sw))

    def test_solve_engine_swapfree(self):
        from tpu_jordan.driver import solve

        r = solve(96, 8, workers=4, dtype=jnp.float64, engine="swapfree")
        assert r.residual < 1e-9 * 96 * 95
        assert r.kappa is not None

    def test_solve_engine_swapfree_no_gather(self):
        # swapfree × gather=False is legal since the bucketed-ppermute
        # permutation (parallel/permute.py): the pod-scale comm engine
        # in the pod-scale memory mode.
        from tpu_jordan.driver import solve

        r = solve(96, 8, workers=4, dtype=jnp.float64, engine="swapfree",
                  gather=False)
        assert r.inverse is None
        assert r.inverse_blocks.shape == (12, 8, 96)
        assert r.residual < 1e-9 * 96 * 95

    def test_swapfree_usage_errors(self):
        from tpu_jordan.driver import UsageError, solve
        from tpu_jordan.models import JordanSolver

        with pytest.raises(UsageError):
            solve(64, 8, engine="swapfree")          # single device
        with pytest.raises(UsageError):
            solve(64, 8, workers=4, engine="swapfree", group=2)
        with pytest.raises(UsageError):
            JordanSolver(64, 8, engine="swapfree")   # single device


class TestDriverEngineSelection:
    def test_inplace_is_default_1d_engine(self):
        from tpu_jordan.driver import _Dist1D

        be = _Dist1D(4, 64, 8)
        assert be.inplace            # Nr=8 <= MAX_UNROLL_NR

    def test_inplace_covers_large_nr(self):
        # Nr=128 > MAX_UNROLL_NR used to fall back to the augmented 4N³
        # path; the 2N³ fori engine now covers it (VERDICT r3 item #1).
        from tpu_jordan.driver import _Dist1D, solve

        be = _Dist1D(4, 1024, 8)     # Nr=128 > 64
        assert be.inplace
        r = solve(544, 8, workers=4, dtype=jnp.float64)  # Nr=68
        assert r.residual < 1e-8 * 544

    def test_no_gather_solve_uses_inplace_blocks(self):
        # gather=False on the in-place engine: inverse_blocks is the whole
        # (Nr, m, N) output and the distributed residual accepts it.
        from tpu_jordan.driver import solve

        r = solve(96, 8, workers=4, gather=False, dtype=jnp.float64)
        assert r.inverse is None
        assert r.inverse_blocks.shape == (12, 8, 96)
        assert r.residual < 1e-10 * 96 * 95



class TestLookahead1D:
    """The 1D probe-ahead engine (ISSUE 16): step t+1's condition probe
    — candidate panel, batched inverses, composite-key pmin — issues
    right after the critical panel, BEFORE the trailing eliminate, so
    the cross-worker reduction overlaps the bulk rank-m GEMM.  Same
    arithmetic in a reordered schedule: bits, pivot sequence, and the
    collective multiset (tests/test_comm.py) pin identical to the plain
    1D engine."""

    @pytest.mark.parametrize("n,m", [
        (64, 8),
        pytest.param(128, 16, marks=pytest.mark.slow)])
    def test_bitmatches_inplace(self, rng, mesh8, n, m):
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        x_p, s_p = sharded_jordan_invert_inplace(a, mesh8, m)
        x_l, s_l = sharded_jordan_invert_inplace(a, mesh8, m,
                                                 lookahead=True)
        assert bool(s_p) == bool(s_l) is False
        assert bool(jnp.all(x_p == x_l)), \
            "1D probe-ahead engine diverged bitwise from inplace"

    @pytest.mark.smoke      # the 1D probe-ahead engine-parity case
    def test_tied_pivots_and_forced_swaps_bitmatch(self, mesh4):
        # |i-j|: zero diagonal forces a swap every superstep AND repeats
        # candidate blocks exactly — the carried decision must reproduce
        # the in-loop probe's lowest-global-row tie rule; ragged n puts
        # the identity-padded tail inside the carried panel.  n kept at
        # the smallest ragged size with a swap per superstep (smoke
        # budget: the unrolled trace cost scales with Nr).
        a = generate("absdiff", (44, 44), jnp.float64)
        x_p, s_p = sharded_jordan_invert_inplace(a, mesh4, 8)
        x_l, s_l = sharded_jordan_invert_inplace(a, mesh4, 8,
                                                 lookahead=True)
        assert bool(s_p) == bool(s_l) is False
        assert bool(jnp.all(x_p == x_l))

    def test_singular_collective_agreement(self, mesh4):
        _, sing = sharded_jordan_invert_inplace(
            jnp.ones((64, 64), jnp.float64), mesh4, 8, lookahead=True)
        assert bool(sing)

    def test_driver_engine_string_routes_and_bitmatches(self, mesh4):
        from tpu_jordan.driver import solve

        r_l = solve(64, 8, workers=4, dtype=jnp.float64,
                    engine="lookahead", gather=False)
        r_p = solve(64, 8, workers=4, dtype=jnp.float64,
                    engine="inplace", gather=False)
        assert r_l.engine == "lookahead"
        assert bool(jnp.all(jnp.asarray(r_l.inverse_blocks)
                            == jnp.asarray(r_p.inverse_blocks)))

    def test_usage_gates_are_typed(self, mesh4, rng):
        # Composition gates: the panel/trailing split is defined on the
        # plain per-step schedule only, and only for the unrolled trace.
        from tpu_jordan.driver import UsageError
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        with pytest.raises(UsageError, match="swapfree/group"):
            sharded_jordan_invert_inplace(a, mesh4, 8, lookahead=True,
                                          swapfree=True)
        with pytest.raises(UsageError, match="swapfree/group"):
            sharded_jordan_invert_inplace(a, mesh4, 8, lookahead=True,
                                          group=2)
        n_big = 8 * (MAX_UNROLL_NR + 4)
        a_big = jnp.asarray(rng.standard_normal((n_big, n_big)),
                            jnp.float32)
        with pytest.raises(UsageError, match="unrolled-only"):
            sharded_jordan_invert_inplace(a_big, mesh4, 8,
                                          lookahead=True)
