"""Tests for the blocked Gauss–Jordan inversion (ops/jordan.py).

Covers the reference's correctness gates (SURVEY.md §4): residual
‖A·A⁻¹ − I‖∞ on the default |i−j| fixture, Hilbert golden residuals and the
n>=10 singularity cliff at EPS=1e-15 (main.cpp:7, 782, 1075-1083), plus
parity against jnp.linalg.inv on random matrices.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.ops import (
    block_jordan_invert,
    generate,
    residual_inf_norm,
)


def invert64(a, m, **kw):
    a = jnp.asarray(a, jnp.float64)
    return block_jordan_invert(a, block_size=m, **kw)


class TestRandomParity:
    @pytest.mark.parametrize("n,m", [(8, 4), (16, 16), (33, 8), (64, 16)])
    def test_matches_linalg_inv(self, rng, n, m):
        a = rng.standard_normal((n, n))
        inv, sing = invert64(a, m)
        assert not bool(sing)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_ragged_padding(self, rng):
        # n not a multiple of m exercises the identity-padding path that
        # replaces the reference's ragged last block (main.cpp:133-137).
        a = rng.standard_normal((37, 37))
        inv, sing = invert64(a, 8)
        assert not bool(sing)
        assert inv.shape == (37, 37)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )


class TestDefaultFixture:
    @pytest.mark.parametrize("n,m", [(64, 16), (128, 32), (200, 48)])
    def test_absdiff_residual(self, n, m):
        # Default generator f=|i−j| has a zero diagonal: inverting it
        # *requires* pivoting (main.cpp:47-57).
        a = generate("absdiff", (n, n), jnp.float64)
        inv, sing = invert64(a, m)
        assert not bool(sing)
        # Absolute residual scales with ‖A‖∞ ≈ n²/2 and the conditioning;
        # gate on the norm-relative residual instead of a fixed cutoff.
        res = float(residual_inf_norm(a, inv))
        rel = res / float(jnp.max(jnp.sum(jnp.abs(a), axis=1)))
        assert rel < 1e-11, f"relative residual {rel} too large (abs {res})"

    def test_zero_diagonal_small(self):
        a = jnp.asarray([[0.0, 1.0], [1.0, 0.0]], jnp.float64)
        inv, sing = invert64(a, 2)
        assert not bool(sing)
        np.testing.assert_allclose(np.asarray(inv), np.asarray(a), atol=1e-14)


class TestHilbertGoldens:
    # Reference golden residuals (BASELINE.md, single-rank -DHILBERT runs):
    # n=4 → 2.9e−13, n=6 → 1.7e−9, n=8 → 2.3e−6.  Raw GJ residual on such
    # ill-conditioned matrices is rounding-ordering luck (XLA's FMA fusion
    # rounds differently from the C++ loop), so the raw bound is loose; with
    # two Newton–Schulz refinement steps we must sit at the u·cond floor,
    # i.e. within a small factor of the goldens.
    @pytest.mark.parametrize("n,golden", [(4, 2.9e-13), (6, 1.7e-9), (8, 2.3e-6)])
    def test_hilbert_residual(self, n, golden):
        a = generate("hilbert", (n, n), jnp.float64)
        inv, sing = invert64(a, n)
        assert not bool(sing)
        res = float(residual_inf_norm(a, inv))
        assert res < golden * 1e3

    @pytest.mark.parametrize("n,golden", [(4, 2.9e-13), (6, 1.7e-9), (8, 2.3e-6)])
    def test_hilbert_residual_refined(self, n, golden):
        a = generate("hilbert", (n, n), jnp.float64)
        inv, sing = invert64(a, n, refine=2)
        assert not bool(sing)
        res = float(residual_inf_norm(a, inv))
        assert res < golden * 5

    @pytest.mark.parametrize("n", [13, 14, 16])
    def test_hilbert_singular_cliff(self, n):
        # Reference behavior: Hilbert hits the EPS=1e-15 relative-threshold
        # singularity cliff at n>=10 (BASELINE.md; main.cpp:7,782).  The
        # exact crossing point is rounding-ordering luck — XLA's FMA fusion
        # gives slightly larger pivots, so our cliff sits at n=13 (we
        # successfully invert H12, cond≈1.7e16; the semantic contract — the
        # same relative threshold rule — is identical).
        a = generate("hilbert", (n, n), jnp.float64)
        _, sing = invert64(a, n)
        assert bool(sing)

    @pytest.mark.parametrize("n", [10, 12])
    def test_hilbert_pre_cliff_inverts(self, n):
        # Sizes the reference rejects but we invert (better, not different:
        # the inverse is real, as the residual proves).
        a = generate("hilbert", (n, n), jnp.float64)
        inv, sing = invert64(a, n, refine=2)
        assert not bool(sing)
        res = float(residual_inf_norm(a, inv))
        assert res < 1.0


class TestSingularity:
    def test_rank_deficient_flagged(self):
        a = jnp.ones((8, 8), jnp.float64)
        _, sing = invert64(a, 4)
        assert bool(sing)

    def test_zero_matrix_flagged(self):
        a = jnp.zeros((8, 8), jnp.float64)
        _, sing = invert64(a, 4)
        assert bool(sing)

    def test_singular_does_not_poison_flag(self, rng):
        # A valid matrix next to a singular one: flags stay independent.
        good = rng.standard_normal((8, 8))
        _, sing = invert64(good, 4)
        assert not bool(sing)


class TestDtypes:
    def test_float32(self, rng):
        a = jnp.asarray(rng.standard_normal((32, 32)), jnp.float32)
        inv, sing = block_jordan_invert(a, block_size=8)
        assert not bool(sing)
        res = float(residual_inf_norm(a, inv))
        assert res < 1e-3

    def test_block_size_larger_than_n(self, rng):
        a = rng.standard_normal((5, 5))
        inv, sing = invert64(a, 64)
        assert not bool(sing)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )
