"""ISSUE 10 tentpole part 1 — the per-superstep numerics observatory.

Pins: the trace rides the SAME executable and never changes the
inverse's bits; the per-step records are the paper's own selection
evidence (pivot id in the live window, the chosen criterion value is
the candidate minimum); both non-off modes mirror into the
``tpu_jordan_pivot_condition``/``growth_factor``/``residual``
histograms; spikes land in the flight recorder BEFORE any recovery
rung (the causal-chain acceptance, checker-validated both ways); and
the ``off`` default costs the warm path nothing — no report, no
recorder events, no histogram series.
"""

import importlib.util
import pathlib

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.driver import UsageError, solve
from tpu_jordan.obs import numerics as obs_numerics
from tpu_jordan.obs.metrics import REGISTRY
from tpu_jordan.obs.recorder import RECORDER

_tool = (pathlib.Path(__file__).resolve().parent.parent / "tools"
         / "check_numerics.py")
_spec = importlib.util.spec_from_file_location("check_numerics", _tool)
check_numerics = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_numerics)


def _hist_count(name, **labels):
    h = REGISTRY.histogram(name)
    res = h._series.get(tuple(sorted((str(k), str(v))
                                     for k, v in labels.items())))
    return 0 if res is None else res.count


class TestModes:
    def test_resolve_mode_vocabulary(self):
        assert obs_numerics.resolve_mode(None) == "off"
        for m in ("off", "summary", "trace"):
            assert obs_numerics.resolve_mode(m) == m
        with pytest.raises(UsageError):
            obs_numerics.resolve_mode("verbose")

    def test_off_default_costs_nothing(self):
        """The warm-path pin: the default solve produces no report, no
        recorder events, and moves no numerics histogram."""
        before_ev = RECORDER.total
        before_res = _hist_count("tpu_jordan_residual", engine="inplace")
        r = solve(48, 16, generator="rand", engine="inplace")
        assert r.numerics is None
        assert RECORDER.total == before_ev
        assert _hist_count("tpu_jordan_residual",
                           engine="inplace") == before_res


class TestTrace:
    def test_trace_records_every_superstep_and_bitmatches(self):
        """One record per superstep; the pivot id sits in the live
        window; the chosen criterion value is the candidate minimum;
        and the inverse BIT-MATCHES the uninstrumented solve — the
        stats are reads, never a different computation."""
        plain = solve(48, 16, generator="rand", engine="inplace")
        r = solve(48, 16, generator="rand", engine="inplace",
                  numerics="trace")
        rep = r.numerics
        nr = 3
        assert rep.mode == "trace" and rep.trace_engine == "inplace"
        assert len(rep.pivot_block) == nr
        for t, p in enumerate(rep.pivot_block):
            assert t <= p < nr
        for mn, mx in zip(rep.pivot_inv_norm, rep.cand_norm_max):
            assert np.isfinite(mn) and mn <= mx
        assert all(s == 0 for s in rep.singular_candidates)
        assert len(rep.growth) == nr
        # growth is a running watermark: non-decreasing.
        assert all(a <= b + 1e-12 for a, b in zip(rep.growth,
                                                  rep.growth[1:]))
        assert rep.growth_factor is not None and rep.growth_factor > 0
        # The MODELED field is named as modeled — nothing else is.
        assert rep.modeled_fields == ("residual_est",)
        assert len(rep.residual_est) == nr
        np.testing.assert_array_equal(np.asarray(plain.inverse),
                                      np.asarray(r.inverse))

    def test_grouped_trace_same_pivot_sequence(self):
        """The grouped engine's eager side-updates preserve the pivot
        RULE (ISSUE 6 contract): its trace shows the same pivot
        sequence as the plain engine on the same fixture."""
        a = solve(64, 16, generator="rand", engine="inplace",
                  numerics="trace")
        b = solve(64, 16, generator="rand", engine="grouped",
                  numerics="trace")
        assert b.numerics.trace_engine == "grouped"
        assert a.numerics.pivot_block == b.numerics.pivot_block

    def test_trace_mirrors_into_registry(self):
        before_p = _hist_count("tpu_jordan_pivot_condition",
                               engine="inplace")
        before_g = _hist_count("tpu_jordan_growth_factor",
                               engine="inplace")
        r = solve(48, 16, generator="rand", engine="inplace",
                  numerics="trace")
        nr = len(r.numerics.pivot_block)
        assert _hist_count("tpu_jordan_pivot_condition",
                           engine="inplace") == before_p + nr
        assert _hist_count("tpu_jordan_growth_factor",
                           engine="inplace") == before_g + 1

    def test_trace_refusals_are_typed(self):
        """No silently different trace: the host-opaque paths refuse."""
        from tpu_jordan.driver import single_device_invert

        with pytest.raises(UsageError, match="augmented"):
            single_device_invert(64, 16, "augmented",
                                 collect_stats=True)
        with pytest.raises(UsageError, match="bf16"):
            single_device_invert(64, 16, "grouped_pallas_bf16", 2,
                                 collect_stats=True)
        with pytest.raises(UsageError, match="MAX_UNROLL_NR"):
            single_device_invert(65 * 8, 8, "inplace",
                                 collect_stats=True)
        with pytest.raises(UsageError, match="distributed"):
            solve(32, 8, generator="rand", workers=2, numerics="trace")

    def test_pallas_fp32_traces_through_grouped_twin(self):
        """The fp32 fused engine's trace instruments its bit-matching
        XLA twin — the returned callable exists and is the grouped
        instrumented path (no UsageError)."""
        from tpu_jordan.driver import single_device_invert

        fn = single_device_invert(64, 16, "grouped_pallas", 2,
                                  collect_stats=True)
        assert fn is not None


class TestSummary:
    def test_summary_reads_only_returned_numbers(self):
        r = solve(48, 16, generator="rand", engine="inplace",
                  numerics="summary")
        rep = r.numerics
        assert rep.mode == "summary"
        assert rep.rel_residual == pytest.approx(r.rel_residual)
        assert rep.kappa == pytest.approx(r.kappa)
        assert rep.pivot_block is None and rep.growth is None
        assert rep.to_json()["mode"] == "summary"

    def test_summary_on_distributed_mesh(self):
        r = solve(32, 8, generator="rand", workers=2,
                  numerics="summary")
        assert r.numerics is not None
        assert r.numerics.mode == "summary"
        assert np.isfinite(r.numerics.rel_residual)


class TestSpikes:
    def test_healthy_solve_spikes_nothing(self):
        r = solve(48, 16, generator="rand", engine="inplace",
                  numerics="trace")
        assert r.numerics.spikes == []

    def test_ill_conditioned_ladder_causally_explained(self, tmp_path):
        """THE ISSUE 10 acceptance pin: a seeded ill-conditioned bf16
        solve under the fp32-SLO policy walks refine -> fp32 re-solve,
        and every recovery_rung / residual_gate_failure event in the
        flight recorder is preceded (by seq) by a numerics_spike."""
        from tpu_jordan.io import write_matrix_file
        from tpu_jordan.resilience import ResiliencePolicy

        n = 16
        path = str(tmp_path / "ill.mat")
        write_matrix_file(path, obs_numerics.ill_conditioned(n))
        mark = RECORDER.total
        pol = ResiliencePolicy(gate_dtype="float32")
        r = solve(n, 8, file=path, dtype=jnp.bfloat16, policy=pol,
                  numerics="trace")
        assert [x["rung"] for x in r.recovery] == ["refine", "resolve"]
        events = RECORDER.since(mark)
        spike_seqs = [e["seq"] for e in events
                      if e["kind"] == "numerics_spike"]
        assert spike_seqs, "an ill-conditioned trace must spike"
        rungs = [e for e in events
                 if e["kind"] in ("recovery_rung",
                                  "residual_gate_failure")]
        assert len(rungs) == 3      # gate failure + 2 rungs
        for e in rungs:
            assert any(s < e["seq"] for s in spike_seqs), \
                f"{e['kind']} seq {e['seq']} has no preceding spike"
        # The report carries the spike ledger too.
        assert any(s["signal"] == "residual"
                   for s in r.numerics.spikes)

    def test_policy_gate_threshold_bounds_spike_threshold(self):
        """With a policy attached the residual spike threshold IS the
        gate threshold — a gate failure can never outrun its spike."""
        from tpu_jordan.resilience import ResiliencePolicy
        from tpu_jordan.resilience.degrade import gate_threshold

        pol = ResiliencePolicy(gate_dtype="float32")
        rep = obs_numerics.summary_report(
            n=16, block_size=8, engine="inplace", rel_residual=0.4,
            kappa=1e4, norm_a=3.0, dtype=jnp.float32)
        thr = obs_numerics.SpikeThresholds(
            residual=gate_threshold(pol, 16, 1e4, jnp.float32))
        spikes = obs_numerics.record_spikes(
            rep, thr, recorder=lambda *a, **k: None)
        # rel 0.4 > gate 16*eps*16*1e4 ~ 3e-2 -> must spike.
        assert [s["signal"] for s in spikes] == ["residual"]


@pytest.fixture(scope="module")
def demo_report():
    """ONE cached demo run for every checker test (the test_fleet
    cached-report discipline — no extra solves per assertion)."""
    return obs_numerics.numerics_demo(n=16, block_size=8, seed=7)


class TestDemoAndChecker:
    def test_demo_report_passes_checker(self, demo_report):
        errs, unexplained = check_numerics.check(demo_report)
        assert errs == [] and unexplained == []
        assert demo_report["silent_rung"] is False
        assert demo_report["rung_count"] == 2

    def test_checker_rejects_stripped_spikes(self, demo_report):
        """Both-ways: delete the spike events and the causal chain
        breaks — the exit-2 class."""
        import copy

        doctored = copy.deepcopy(demo_report)
        doctored["blackbox"]["events"] = [
            e for e in doctored["blackbox"]["events"]
            if e["kind"] != "numerics_spike"]
        doctored["spike_count"] = 0
        errs, unexplained = check_numerics.check(doctored)
        assert unexplained, "stripped spikes must be unexplained rungs"

    def test_checker_rejects_modeled_masquerade(self, demo_report):
        """A report whose modeled-field ledger drifts (a modeled number
        posing as measured, or vice versa) fails structurally."""
        import copy

        doctored = copy.deepcopy(demo_report)
        doctored["numerics"]["modeled_fields"] = []
        errs, _ = check_numerics.check(doctored)
        assert any("modeled" in e for e in errs)

    def test_checker_cli_exit_taxonomy(self, demo_report, tmp_path):
        import copy
        import json

        good = tmp_path / "good.json"
        good.write_text(json.dumps(demo_report))
        assert check_numerics.main([str(good)]) == 0
        doctored = copy.deepcopy(demo_report)
        doctored["blackbox"]["events"] = [
            e for e in doctored["blackbox"]["events"]
            if e["kind"] != "numerics_spike"]
        doctored["spike_count"] = 0
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(doctored))
        assert check_numerics.main([str(bad)]) == 2
        garbage = tmp_path / "garbage.json"
        garbage.write_text("not json")
        assert check_numerics.main([str(garbage)]) == 1


class TestCliFlagContract:
    """Review findings: --numerics-demo excludes the other demo modes,
    and --numerics is never silently ignored — demo modes that cannot
    honor it refuse typed (exit 1), the serve demo threads it."""

    def test_numerics_demo_excludes_fleet_demo(self):
        from tpu_jordan.__main__ import main

        assert main(["16", "8", "--numerics-demo", "--fleet-demo",
                     "--quiet"]) == 1

    def test_chaos_demo_refuses_numerics(self):
        from tpu_jordan.__main__ import main

        assert main(["96", "32", "--chaos-demo", "--numerics",
                     "summary", "--quiet"]) == 1

    def test_fleet_demo_refuses_numerics(self):
        from tpu_jordan.__main__ import main

        assert main(["96", "32", "--fleet-demo", "--numerics",
                     "summary", "--quiet"]) == 1

    def test_serve_demo_refuses_trace(self):
        """serve_demo threads --numerics into JordanService, whose
        trace refusal is typed — never a silently-off observatory."""
        from tpu_jordan.__main__ import main

        assert main(["96", "32", "--serve-demo", "--numerics",
                     "trace", "--quiet"]) == 1


class TestServeNumerics:
    def test_off_is_the_serve_default(self):
        """The serve-path default is off (the acceptance wording): the
        warm-path pins in test_obs/test_serve all run through this
        default, so the observatory costs the hot path nothing."""
        from tpu_jordan.serve import JordanService

        svc = JordanService(autostart=False)
        try:
            assert svc.numerics == "off"
            assert svc._batcher.numerics == "off"
        finally:
            svc.close()

    def test_trace_is_a_typed_refusal(self):
        from tpu_jordan.serve import JordanService

        with pytest.raises(UsageError, match="trace"):
            JordanService(numerics="trace", autostart=False)

    def test_summary_observes_rider_residuals(self):
        from tpu_jordan.serve import JordanService

        before = _hist_count("tpu_jordan_residual", engine="inplace")
        with JordanService(engine="inplace", batch_cap=2,
                           numerics="summary") as svc:
            rng = np.random.default_rng(3)
            a = rng.standard_normal((24, 24)).astype(np.float32)
            a += 24 * np.eye(24, dtype=np.float32)
            res = svc.invert(a)
        assert not res.singular
        assert _hist_count("tpu_jordan_residual",
                           engine="inplace") == before + 1
