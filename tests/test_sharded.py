"""Distributed tests on the 8-device virtual CPU mesh (conftest.py) —
the TPU-native "mpirun -np 8" (SURVEY.md §4): sharded Jordan inversion,
ring GEMM, distributed residual, collective singularity agreement.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.ops import generate
from tpu_jordan.parallel import (
    distributed_residual,
    make_mesh,
    ring_matmul,
    sharded_jordan_invert,
)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh(4)


class TestRingGemm:
    @pytest.mark.parametrize("n,m", [(64, 8), (96, 16), (100, 8)])
    def test_matches_matmul(self, rng, mesh8, n, m):
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        b = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        d = ring_matmul(a, b, mesh8, m)
        np.testing.assert_allclose(
            np.asarray(d), np.asarray(a) @ np.asarray(b), rtol=1e-12, atol=1e-12
        )

    def test_four_workers(self, rng, mesh4):
        a = jnp.asarray(rng.standard_normal((32, 32)), jnp.float64)
        b = jnp.asarray(rng.standard_normal((32, 32)), jnp.float64)
        d = ring_matmul(a, b, mesh4, 8)
        np.testing.assert_allclose(
            np.asarray(d), np.asarray(a) @ np.asarray(b), rtol=1e-12, atol=1e-12
        )


class TestShardedJordan:
    @pytest.mark.parametrize("n,m", [(64, 8), (128, 16), (100, 8)])
    def test_matches_linalg_inv(self, rng, mesh8, n, m):
        a = jnp.asarray(rng.standard_normal((n, n)), jnp.float64)
        inv, sing = sharded_jordan_invert(a, mesh8, m)
        assert not bool(sing)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(np.asarray(a)), rtol=1e-7, atol=1e-7
        )

    def test_absdiff_needs_pivoting(self, mesh8):
        a = generate("absdiff", (128, 128), jnp.float64)
        inv, sing = sharded_jordan_invert(a, mesh8, 16)
        assert not bool(sing)
        res = float(distributed_residual(a, inv, mesh8, 16))
        rel = res / float(jnp.max(jnp.sum(jnp.abs(a), axis=1)))
        assert rel < 1e-11

    def test_matches_single_device(self, rng, mesh4):
        from tpu_jordan.ops import block_jordan_invert

        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        inv_d, s_d = sharded_jordan_invert(a, mesh4, 8)
        inv_s, s_s = block_jordan_invert(a, block_size=8)
        assert bool(s_d) == bool(s_s) is False
        # Same algorithm, same pivot rule -> results agree to rounding.
        np.testing.assert_allclose(
            np.asarray(inv_d), np.asarray(inv_s), rtol=1e-9, atol=1e-9
        )

    def test_tied_pivots_match_single_device(self, mesh4):
        # |i-j| has exactly-repeated candidate blocks, so pivot keys tie;
        # the sharded reduction must resolve ties to the lowest *global*
        # block row like the single-device argmin, not the lowest worker.
        from tpu_jordan.ops import block_jordan_invert

        a = generate("absdiff", (96, 96), jnp.float64)
        inv_d, s_d = sharded_jordan_invert(a, mesh4, 8)
        inv_s, s_s = block_jordan_invert(a, block_size=8)
        assert bool(s_d) == bool(s_s) is False
        np.testing.assert_allclose(
            np.asarray(inv_d), np.asarray(inv_s), rtol=1e-9, atol=1e-12
        )

    def test_singular_collective_agreement(self, mesh8):
        a = jnp.ones((64, 64), jnp.float64)
        _, sing = sharded_jordan_invert(a, mesh8, 8)
        assert bool(sing)

    def test_hilbert_distributed(self, mesh4):
        a = generate("hilbert", (8, 8), jnp.float64)
        inv, sing = sharded_jordan_invert(a, mesh4, 2)
        assert not bool(sing)
        res = float(distributed_residual(a, inv, mesh4, 2))
        assert res < 1e-3  # cond(H8) ~ 1e10; fp64 floor


class TestDistributedResidual:
    def test_identity(self, mesh8):
        eye = jnp.eye(64, dtype=jnp.float64)
        res = float(distributed_residual(eye, eye, mesh8, 8))
        assert res == 0.0
