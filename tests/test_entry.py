"""The driver contract: entry() compiles; dryrun_multichip really validates
an n-device mesh (the round-1 failure mode was a silent 1-device mesh)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def test_make_mesh_raises_on_too_few_devices():
    from tpu_jordan.parallel import make_mesh

    with pytest.raises(ValueError, match="workers"):
        make_mesh(1024)


@pytest.mark.smoke          # the entry-point case
def test_entry_compiles():
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn).lower(*args).compile()(*args)
    assert out.shape == args[0].shape


@pytest.mark.slow
def test_dryrun_inline_on_8_fake_devices():
    # conftest forces 8 virtual CPU devices, so the inline path runs and
    # its internal mesh-size assertion proves 8-way collectives executed.
    # slow since ISSUE 2 (the 18-leg dryrun grew past 45 s): the same
    # legs run every round through the MULTICHIP harness and the
    # unmarked nightly suite; tier-1 keeps the per-engine parity units
    # plus the engine=auto legs in test_scale_demo.py.
    import __graft_entry__ as g

    g._dryrun_impl(8)


@pytest.mark.slow
def test_dryrun_subprocess_path():
    # The driver calls dryrun_multichip from an arbitrary backend state;
    # the subprocess fallback must work even when the parent env pins a
    # different platform.  Exercise the real public entry in a child with
    # no device-count forcing at all.
    env = {k: v for k, v in os.environ.items()
           if "xla_force_host_platform_device_count" not in v.lower()
           or k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(4)"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "1D mesh p=4 ok" in proc.stdout
