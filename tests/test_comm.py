"""ISSUE 14 — the communication observatory.

The reconciliation invariant is the heart: for every distributed
engine configuration, the multiset of collectives the TRACED program
actually issues (recorded by ``parallel/compat.py``'s shims — kind ×
mesh axis × operand shape × dtype) must EQUAL the layout-derived
analytical inventory (``obs/comm.engine_report``).  Plus: the driver
integration (``SolveResult.comm``, execute-span attrs, the
``tpu_jordan_comm_*`` counters), measured-vs-projected drift (judged
backends only; out-of-band = a recorded ``comm_drift`` event), the
warm-serve zero-compile/zero-measurement pins WITH recording enabled,
the opt-in registry cost-hook calibration, and the
``tools/check_comm.py`` both-ways gate (stripped-collective and
forged-drift doctorings exit 2).

Config hygiene: jax caches lowerings per (function, avals, statics) —
a cache-hit compile has no fresh trace to observe, so every
reconciliation test here uses a problem size no other test in this
module compiles (the conftest clears jax caches per MODULE, so
cross-module reuse is moot).
"""

import importlib.util
import json
import pathlib

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.driver import solve
from tpu_jordan.obs import comm
from tpu_jordan.obs.metrics import REGISTRY
from tpu_jordan.obs.recorder import RECORDER
from tpu_jordan.obs.spans import Telemetry
from tpu_jordan.ops import generate
from tpu_jordan.parallel import make_mesh, make_mesh_2d
from tpu_jordan.parallel.layout import CyclicLayout, CyclicLayout2D

_repo = pathlib.Path(__file__).resolve().parent.parent
_spec = importlib.util.spec_from_file_location(
    "check_comm", _repo / "tools" / "check_comm.py")
check_comm = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check_comm)


# ---------------------------------------------------------------------
# Analytical model: pure host-side layout math.
# ---------------------------------------------------------------------


class TestAnalytical:
    def test_1d_plain_inventory(self):
        """The unrolled plain 1D engine: 6 collectives per superstep
        (3 scalar pivot rounds + H + two (m, N) row psums — the
        comm_model inventory), all traced (unrolled) and all
        executed."""
        lay = CyclicLayout.create(64, 8, 4)          # Nr = 8
        rep = comm.engine_report(engine="inplace", lay=lay,
                                 dtype="float32", gather=True)
        eng = [s for s in rep.sigs if s.section == "engine"]
        assert sum(s.executed for s in eng) == 6 * lay.Nr
        assert sum(s.traced for s in eng) == 6 * lay.Nr
        rows = [s for s in eng if s.phase in ("row_bcast",
                                              "row_exchange")]
        assert {s.shape for s in rows} == {(8, lay.N)}
        assert sum(s.payload_bytes * s.executed for s in rows) == (
            2 * lay.Nr * 8 * lay.N * 4)

    def test_fori_traces_once_executes_nr(self):
        lay = CyclicLayout.create(64, 8, 4)
        rep = comm.engine_report(engine="inplace", lay=lay,
                                 dtype="float32", unroll=False)
        eng = [s for s in rep.sigs if s.section == "engine"]
        assert sum(s.traced for s in eng) == 6
        assert sum(s.executed for s in eng) == 6 * lay.Nr

    def test_swapfree_halves_row_bytes_and_adds_permute(self):
        """The swap-free design claim, as accounting: ONE (m, N) row
        psum per step instead of two, and p−1 shard-size ppermute
        rounds at the end."""
        lay = CyclicLayout.create(64, 8, 4)
        plain = comm.engine_report(engine="inplace", lay=lay,
                                   dtype="float32")
        sf = comm.engine_report(engine="swapfree", lay=lay,
                                dtype="float32")

        def row_bytes(rep):
            return sum(s.payload_bytes * s.executed for s in rep.sigs
                       if s.phase in ("row_bcast", "row_exchange"))

        assert row_bytes(sf) * 2 == row_bytes(plain)
        perms = [s for s in sf.sigs if s.phase == "permute"]
        assert len(perms) == 1 and perms[0].executed == lay.p - 1
        assert perms[0].shape == (lay.blocks_per_worker, 8, lay.N)
        assert not any(s.phase == "permute" for s in plain.sigs)

    def test_dtype_width_scales_bulk_bytes(self):
        lay = CyclicLayout.create(64, 8, 4)
        f32 = comm.engine_report(engine="inplace", lay=lay,
                                 dtype="float32")
        f64 = comm.engine_report(engine="inplace", lay=lay,
                                 dtype="float64")

        def bulk(rep):
            return sum(s.payload_bytes * s.executed for s in rep.sigs
                       if s.phase == "row_bcast")

        assert bulk(f64) == 2 * bulk(f32)

    def test_ragged_n_accounts_padded_layout(self):
        """A ragged n (n % m != 0) pads to Nr·m — the inventory's
        shapes are the PADDED geometry the engines actually move."""
        lay = CyclicLayout.create(20, 8, 4)           # Nr 3 -> 4
        assert lay.N == 32 and lay.n == 20
        rep = comm.engine_report(engine="inplace", lay=lay,
                                 dtype="float32")
        rows = [s for s in rep.sigs if s.phase == "row_bcast"]
        assert rows[0].shape == (8, 32)
        assert rows[0].executed == lay.Nr == 4

    def test_grouped_tail_stacks_narrower(self):
        """Nr=8, k=3 → groups of 3, 3, 2: the stacked psum width is
        N + kg·m + m per group, so the tail group's signature is its
        own (narrower) entry."""
        lay = CyclicLayout.create(64, 8, 4)           # Nr = 8
        rep = comm.engine_report(engine="grouped", lay=lay,
                                 dtype="float32", group=3)
        widths = {s.shape[-1] for s in rep.sigs
                  if s.phase == "row_bcast"}
        assert widths == {lay.N + 3 * 8 + 8, lay.N + 2 * 8 + 8}

    def test_2d_inventory_axes(self):
        """2D: the panel broadcast and swap fix-up ride "pc", the row
        psums "pr", the pivot reduction the whole mesh — data moves
        only along the axis that shards it."""
        lay = CyclicLayout2D.create(64, 8, 2, 4)
        rep = comm.engine_report(engine="inplace", lay=lay,
                                 dtype="float32")
        by_phase = {}
        for s in rep.sigs:
            by_phase.setdefault(s.phase, set()).add(s.axis)
        assert by_phase["panel_bcast"] == {"pc"}
        assert by_phase["row_bcast"] == {"pr"}
        assert "pr,pc" in by_phase["pivot"]
        assert by_phase["unscramble"] == {"pc"}

    def test_gather_implicit_and_refine_drops_residual(self):
        lay = CyclicLayout.create(64, 8, 4)
        rep = comm.engine_report(engine="inplace", lay=lay,
                                 dtype="float32", gather=True)
        g = [s for s in rep.sigs if s.section == "gather"]
        assert len(g) == 1 and g[0].implicit
        # Implicit entries never enter the reconciliation multiset.
        assert g[0].key() not in rep.expected_traced("gather")
        assert any(s.section == "residual" for s in rep.sigs)
        rep_r = comm.engine_report(engine="inplace", lay=lay,
                                   dtype="float32", gather=True,
                                   refine=1)
        assert not any(s.section == "residual" for s in rep_r.sigs)
        rep_ng = comm.engine_report(engine="inplace", lay=lay,
                                    dtype="float32", gather=False)
        assert not any(s.section == "gather" for s in rep_ng.sigs)

    def test_totals_add_up(self):
        lay = CyclicLayout2D.create(48, 8, 2, 2)
        rep = comm.engine_report(engine="swapfree", lay=lay,
                                 dtype="float32", gather=False)
        j = rep.to_json()
        assert j["totals"]["payload_bytes"] == sum(
            s["payload_bytes"] * s["executed"] for s in j["sigs"])
        assert j["totals"]["messages"] == sum(
            s["executed"] for s in j["sigs"] if not s["implicit"])


# ---------------------------------------------------------------------
# The reconciliation invariant: observed == analytical per engine.
# ---------------------------------------------------------------------


def _reconcile_1d(n, m, p, engine, group=0, unroll=None,
                  swapfree=False, lookahead=False):
    from tpu_jordan.parallel.ring_gemm import _to_identity_padded_blocks
    from tpu_jordan.parallel.sharded_inplace import (
        compile_sharded_jordan_inplace,
    )

    mesh = make_mesh(p)
    lay = CyclicLayout.create(n, m, p)
    a = generate("absdiff", (n, n), jnp.float32)
    W = _to_identity_padded_blocks(a, lay, mesh)
    rep = comm.engine_report(engine=engine, lay=lay, dtype="float32",
                             gather=True, group=group, unroll=unroll)
    with comm.record_collectives() as rec:
        compile_sharded_jordan_inplace(W, mesh, lay, group=group,
                                       unroll=unroll,
                                       swapfree=swapfree,
                                       lookahead=lookahead)
    rep.attach_observed("engine", rec.records)
    return rep


def _reconcile_2d(n, m, pr, pc, engine, group=0, unroll=None,
                  swapfree=False, lookahead=False):
    from tpu_jordan.parallel.jordan2d import scatter_matrix_2d
    from tpu_jordan.parallel.jordan2d_inplace import (
        compile_sharded_jordan_inplace_2d,
    )

    mesh = make_mesh_2d(pr, pc)
    lay = CyclicLayout2D.create(n, m, pr, pc)
    a = generate("absdiff", (n, n), jnp.float32)
    W = scatter_matrix_2d(a, lay, mesh)
    rep = comm.engine_report(engine=engine, lay=lay, dtype="float32",
                             gather=True, group=group, unroll=unroll)
    with comm.record_collectives() as rec:
        compile_sharded_jordan_inplace_2d(W, mesh, lay, group=group,
                                          unroll=unroll,
                                          swapfree=swapfree,
                                          lookahead=lookahead)
    rep.attach_observed("engine", rec.records)
    return rep


class TestReconciliation:
    """Each case compiles a UNIQUE configuration (fresh trace
    guaranteed) and pins observed == analytical, multiset-exact over
    (kind, axis, shape, dtype)."""

    @pytest.mark.parametrize("engine,group,unroll,swapfree", [
        ("inplace", 0, True, False),
        ("inplace", 0, False, False),
        ("grouped", 2, True, False),
        ("grouped", 3, False, False),      # fori + ragged group tail
        ("swapfree", 0, None, True),
    ])
    def test_1d_engines(self, engine, group, unroll, swapfree):
        rep = _reconcile_1d(24, 8, 4, engine, group=group,
                            unroll=unroll, swapfree=swapfree)
        assert rep.reconciled is True, rep.mismatches

    @pytest.mark.parametrize("engine,group,unroll,swapfree", [
        ("inplace", 0, True, False),
        ("inplace", 0, False, False),
        ("grouped", 2, True, False),
        ("swapfree", 0, None, True),
    ])
    def test_2d_engines(self, engine, group, unroll, swapfree):
        rep = _reconcile_2d(24, 8, 2, 2, engine, group=group,
                            unroll=unroll, swapfree=swapfree)
        assert rep.reconciled is True, rep.mismatches

    @pytest.mark.slow
    def test_2d_grouped_fori_tail_2x4(self):
        """The heaviest twin: 2×4 mesh, fori grouped with a tail —
        tier-1 keeps the 2×2 unrolled sibling above."""
        rep = _reconcile_2d(40, 8, 2, 4, "grouped", group=3,
                            unroll=False)
        assert rep.reconciled is True, rep.mismatches

    def test_1d_augmented(self):
        from tpu_jordan.parallel.sharded_jordan import (
            compile_sharded_jordan, scatter_augmented,
        )

        mesh = make_mesh(4)
        lay = CyclicLayout.create(28, 8, 4)
        a = generate("absdiff", (28, 28), jnp.float32)
        W = scatter_augmented(a, lay, mesh)
        rep = comm.engine_report(engine="augmented", lay=lay,
                                 dtype="float32")
        with comm.record_collectives() as rec:
            compile_sharded_jordan(W, mesh, lay)
        rep.attach_observed("engine", rec.records)
        assert rep.reconciled is True, rep.mismatches

    def test_2d_augmented(self):
        from tpu_jordan.parallel.jordan2d import (
            compile_sharded_jordan_2d, scatter_augmented_2d,
        )

        mesh = make_mesh_2d(2, 2)
        lay = CyclicLayout2D.create(28, 8, 2, 2)
        a = generate("absdiff", (28, 28), jnp.float32)
        W = scatter_augmented_2d(a, lay, mesh)
        rep = comm.engine_report(engine="augmented", lay=lay,
                                 dtype="float32")
        with comm.record_collectives() as rec:
            compile_sharded_jordan_2d(W, mesh, lay)
        rep.attach_observed("engine", rec.records)
        assert rep.reconciled is True, rep.mismatches

    def test_mismatch_is_typed_not_silent(self):
        """A doctored observation (one record dropped) reconciles
        False with a named mismatch — the invariant has teeth."""
        rep = _reconcile_1d(32, 8, 4, "inplace")
        assert rep.reconciled is True
        # Re-attach a stripped copy: drop one psum record.
        eng = list(rep.observed["engine"])
        victim = next(i for i, r in enumerate(eng) if r[0] == "psum")
        del eng[victim]
        rep.attach_observed("engine", eng)
        assert rep.reconciled is False
        assert any("analytical" in m and "observed" in m
                   for m in rep.mismatches)

    @pytest.mark.slow  # tier-1 budget: the engine-matrix reconciliations stay fast
    def test_cache_hit_is_unjudged_never_false(self):
        """Re-compiling an identical configuration hits jax's lowering
        cache — no fresh trace, honestly un-judged (None), never a
        false mismatch."""
        rep1 = _reconcile_1d(36, 8, 4, "inplace")
        assert rep1.reconciled is True
        rep2 = _reconcile_1d(36, 8, 4, "inplace")   # same config
        assert rep2.observed["engine"] is None
        assert rep2.reconciled is None


def _reconcile_solve_1d(n, m, p, k, unroll, lookahead=False):
    from tpu_jordan.parallel.ring_gemm import _to_identity_padded_blocks
    from tpu_jordan.parallel.sharded_inplace import (
        compile_sharded_jordan_solve, scatter_rhs_1d,
    )

    mesh = make_mesh(p)
    lay = CyclicLayout.create(n, m, p)
    a = generate("absdiff", (n, n), jnp.float32)
    b = generate("rand", (n, k), jnp.float32, row_offset=n)
    W = _to_identity_padded_blocks(a, lay, mesh)
    X = scatter_rhs_1d(b, lay, mesh)
    eng = "solve_lookahead" if lookahead else "solve_sharded"
    rep = comm.engine_report(engine=eng, lay=lay,
                             dtype="float32", unroll=unroll, rhs=k)
    with comm.record_collectives() as rec:
        compile_sharded_jordan_solve(W, X, mesh, lay, unroll=unroll,
                                     lookahead=lookahead)
    rep.attach_observed("engine", rec.records)
    return rep


def _reconcile_solve_2d(n, m, pr, pc, k, unroll, lookahead=False):
    from tpu_jordan.parallel.jordan2d import scatter_matrix_2d
    from tpu_jordan.parallel.jordan2d_inplace import (
        compile_sharded_jordan_solve_2d, scatter_rhs_2d,
    )

    mesh = make_mesh_2d(pr, pc)
    lay = CyclicLayout2D.create(n, m, pr, pc)
    a = generate("absdiff", (n, n), jnp.float32)
    b = generate("rand", (n, k), jnp.float32, row_offset=n)
    W = scatter_matrix_2d(a, lay, mesh)
    X = scatter_rhs_2d(b, lay, mesh)
    eng = "solve_lookahead" if lookahead else "solve_sharded"
    rep = comm.engine_report(engine=eng, lay=lay,
                             dtype="float32", unroll=unroll, rhs=k)
    with comm.record_collectives() as rec:
        compile_sharded_jordan_solve_2d(W, X, mesh, lay, unroll=unroll,
                                        lookahead=lookahead)
    rep.attach_observed("engine", rec.records)
    return rep


class TestSolveReconciliation:
    """ISSUE 15: the distributed-solve flavors reconcile multiset-exact
    like every other engine — including the unrolled flavor's
    per-superstep SHRINKING stacked-row shapes (each step its own
    signature), the fori flavor's full-width once-traced rows, and a
    ragged size (padded tail in the inventory)."""

    @pytest.mark.parametrize("unroll", [True, False])
    def test_1d_solve_flavors(self, unroll):
        rep = _reconcile_solve_1d(56, 8, 4, 3, unroll)
        assert rep.reconciled is True, rep.mismatches

    @pytest.mark.slow  # tier-1 budget: the comm-demo fixture's 2D solve leg
    # (check_comm requires solve coverage) reconciles this flavor fast-run;
    # the fori-mesh duplicates below already run nightly
    def test_2d_solve_unrolled(self):
        rep = _reconcile_solve_2d(56, 8, 2, 2, 2, True)
        assert rep.reconciled is True, rep.mismatches

    @pytest.mark.slow   # heavy duplicates of the tier-1 flavors above
    @pytest.mark.parametrize("pr,pc,k,unroll", [
        (2, 4, 1, False),      # fori on the rectangular mesh
        (2, 2, 5, False),
    ])
    def test_2d_solve_fori_meshes(self, pr, pc, k, unroll):
        rep = _reconcile_solve_2d(72, 8, pr, pc, k, unroll)
        assert rep.reconciled is True, rep.mismatches

    def test_ragged_solve_inventory_reconciles(self):
        rep = _reconcile_solve_1d(52, 8, 4, 2, True)   # Nr pads 7 -> 8
        assert rep.reconciled is True, rep.mismatches
        # The shrinking unrolled row shapes really are per-step sigs.
        widths = sorted({s.shape[-1] for s in rep.sigs
                        if s.phase == "row_bcast"})
        assert len(widths) == rep.sigs[0].executed == 8

    def test_solve_report_has_no_residual_section(self):
        lay = CyclicLayout.create(56, 8, 4)
        rep = comm.engine_report(engine="solve_sharded", lay=lay,
                                 dtype="float32", rhs=3)
        assert not [s for s in rep.sigs if s.section == "residual"]
        gather_sigs = [s for s in rep.sigs if s.section == "gather"]
        assert len(gather_sigs) == 1 and gather_sigs[0].implicit
        assert gather_sigs[0].shape == (lay.N, 3)

    def test_unknown_engine_has_no_inventory_and_fails_loudly(self):
        lay = CyclicLayout.create(56, 8, 4)
        with pytest.raises(ValueError, match="inventory"):
            comm.engine_report(engine="solve_sharded_v2", lay=lay,
                               dtype="float32")

    def test_registry_lint_every_distributed_solve_config_accounted(
            self):
        """The ISSUE 15 registry lint: every solve-workload registry
        config that is legal at ANY distributed point must name an
        engine with a registered comm inventory — a new distributed
        engine without accounting fails loudly here, not silently in
        production."""
        from tpu_jordan.tuning.registry import CONFIGS, TunePoint

        points = [
            TunePoint.create(4096, 128, "float32", workers=8,
                             workload=w)
            for w in ("solve", "solve_spd")
        ] + [
            TunePoint.create(4096, 128, "float32", workers=(2, 4),
                             workload=w)
            for w in ("solve", "solve_spd")
        ]
        for cfg in CONFIGS:
            if not cfg.workload.startswith("solve"):
                continue
            if any(cfg.workload == pt.workload and cfg.legal(pt)
                   for pt in points):
                assert cfg.engine in comm.INVENTORY_ENGINES, (
                    f"registry config {cfg.name!r} ({cfg.engine}) is "
                    f"legal at a distributed point but has NO comm "
                    f"inventory (obs/comm.INVENTORY_ENGINES)")

    def test_registry_lint_every_distributed_invert_config_accounted(
            self):
        """The ISSUE 16 extension of the lint above: every INVERT
        registry config legal at a distributed point (that includes
        every new *_lookahead config) names an engine with a
        registered comm inventory."""
        from tpu_jordan.tuning.registry import CONFIGS, TunePoint

        points = [
            TunePoint.create(4096, 128, "float32", workers=8),
            TunePoint.create(4096, 128, "float32", workers=(2, 4)),
        ]
        checked = set()
        for cfg in CONFIGS:
            if cfg.workload != "invert":
                continue
            if any(cfg.legal(pt) for pt in points):
                checked.add(cfg.name)
                assert cfg.engine in comm.INVENTORY_ENGINES, (
                    f"registry config {cfg.name!r} ({cfg.engine}) is "
                    f"legal at a distributed point but has NO comm "
                    f"inventory (obs/comm.INVENTORY_ENGINES)")
        assert "lookahead" in checked   # the ISSUE 16 config IS linted


class TestLookaheadReconciliation:
    """ISSUE 16: the probe-ahead engines reconcile multiset-exact
    against the PLAIN flavors' analytical inventory — the lookahead
    schedule issues step t+1's condition probe one superstep early
    (prologue probe + Nr−1 in-loop probes = the same Nr probes), so
    the collective multiset, and the total payload bytes, are
    IDENTICAL by construction.  Each case compiles a unique size
    (fresh trace; the module's config-hygiene rule)."""

    @pytest.mark.slow  # tier-1 budget: the sharded twin below stays fast-run
    # and the comm-demo fixture's lookahead invert leg reconciles gathered
    def test_1d_invert_lookahead_gathered(self):
        rep = _reconcile_1d(50, 8, 4, "lookahead", lookahead=True)
        assert rep.reconciled is True, rep.mismatches
        # Identical analytical inventory — total payload unchanged.
        lay = CyclicLayout.create(50, 8, 4)
        plain = comm.engine_report(engine="inplace", lay=lay,
                                   dtype="float32", gather=True)
        assert rep.total_bytes() == plain.total_bytes()
        assert rep.total_messages() == plain.total_messages()

    def test_1d_invert_lookahead_sharded(self):
        # gather=False flavor: no implicit all-gather sig, the engine
        # section still reconciles exact on a fresh size.
        from tpu_jordan.parallel.ring_gemm import (
            _to_identity_padded_blocks)
        from tpu_jordan.parallel.sharded_inplace import (
            compile_sharded_jordan_inplace)

        mesh = make_mesh(4)
        lay = CyclicLayout.create(54, 8, 4)
        a = generate("absdiff", (54, 54), jnp.float32)
        W = _to_identity_padded_blocks(a, lay, mesh)
        rep = comm.engine_report(engine="lookahead", lay=lay,
                                 dtype="float32", gather=False)
        with comm.record_collectives() as rec:
            compile_sharded_jordan_inplace(W, mesh, lay,
                                           lookahead=True)
        rep.attach_observed("engine", rec.records)
        assert rep.reconciled is True, rep.mismatches
        assert not [s for s in rep.sigs if s.section == "gather"]

    @pytest.mark.slow       # tier-1 budget: the 1D pins + the dryrun
    def test_2d_invert_lookahead(self):  # 2D legs cover the fast path
        rep = _reconcile_2d(62, 8, 2, 2, "lookahead", lookahead=True)
        assert rep.reconciled is True, rep.mismatches
        lay = CyclicLayout2D.create(62, 8, 2, 2)
        plain = comm.engine_report(engine="inplace", lay=lay,
                                   dtype="float32", gather=True)
        assert rep.total_bytes() == plain.total_bytes()

    @pytest.mark.slow  # tier-1 budget: the comm-demo fixture's lookahead
    # solve leg (pinned by engine name, required by check_comm) reconciles
    # this flavor fast-run
    def test_1d_solve_lookahead(self):
        rep = _reconcile_solve_1d(44, 8, 4, 3, True, lookahead=True)
        assert rep.reconciled is True, rep.mismatches
        lay = CyclicLayout.create(44, 8, 4)
        plain = comm.engine_report(engine="solve_sharded", lay=lay,
                                   dtype="float32", unroll=True, rhs=3)
        assert rep.total_bytes() == plain.total_bytes()
        assert rep.total_messages() == plain.total_messages()

    @pytest.mark.slow       # same tier-1 budget call as the 2D invert
    def test_2d_solve_lookahead(self):
        rep = _reconcile_solve_2d(68, 8, 2, 2, 2, True, lookahead=True)
        assert rep.reconciled is True, rep.mismatches


# ---------------------------------------------------------------------
# Driver + solver integration.
# ---------------------------------------------------------------------


def _counter_total(name: str) -> float:
    snap = REGISTRY.snapshot().get(name, {})
    return sum(s.get("value", 0.0) for s in snap.get("series", []))


class TestDriverIntegration:
    @pytest.mark.smoke
    def test_smoke_1d_solve_totals_exact(self):
        """Smoke tier (ISSUE 14 satellite): a tiny 1D-mesh solve with
        comm accounting on — per-solve totals exactly equal the
        layout-derived prediction, observed == analytical, and the
        counters moved by exactly the analytical amounts."""
        lay = CyclicLayout.create(26, 8, 2)
        expect = comm.engine_report(engine="inplace", lay=lay,
                                    dtype="float32", gather=True)
        b_before = _counter_total("tpu_jordan_comm_bytes_total")
        m_before = _counter_total("tpu_jordan_comm_messages_total")
        with comm.recording():
            res = solve(26, 8, workers=2, engine="inplace")
        rep = res.comm
        assert rep is not None
        assert rep.reconciled is True, rep.mismatches
        assert rep.total_bytes() == expect.total_bytes()
        assert rep.total_messages() == expect.total_messages()
        assert (_counter_total("tpu_jordan_comm_bytes_total")
                - b_before) == rep.total_bytes()
        assert (_counter_total("tpu_jordan_comm_messages_total")
                - m_before) == rep.total_messages()

    def test_execute_span_attrs_and_drift_record(self):
        tel = Telemetry()
        with comm.recording():
            res = solve(26, 8, workers=(2, 2), engine="inplace",
                        telemetry=tel)
        esp = res.trace.find("execute")
        assert esp.attrs["comm_payload_bytes"] == sum(
            s.payload_bytes * s.executed for s in res.comm.sigs
            if s.section == "engine")
        assert esp.attrs["comm_messages"] > 0
        assert "comm_projection_chip" in esp.attrs
        d = res.comm.drift
        assert d is not None and d["judged"] is False  # cpu backend
        assert d["comm_vs_projected"] is not None
        assert d["event_recorded"] is False

    def test_recording_off_still_analytical(self):
        res = solve(26, 8, workers=2, engine="swapfree", gather=False)
        assert res.comm is not None
        assert res.comm.reconciled is None      # nothing observed
        assert res.comm.total_bytes() > 0
        assert any(s.phase == "permute" for s in res.comm.sigs)

    def test_single_device_solve_has_no_comm(self):
        res = solve(16, 8, engine="inplace")
        assert res.comm is None

    def test_solver_model_carries_comm(self):
        from tpu_jordan.models import JordanSolver

        tel = Telemetry()
        sol = JordanSolver(n=30, block_size=8, workers=2,
                           engine="inplace", telemetry=tel)
        a = generate("absdiff", (30, 30), jnp.float32)
        with comm.recording():
            inv, sing = sol.invert(a)
        assert not bool(sing)
        assert sol.comm is not None
        assert sol.comm.reconciled is True, sol.comm.mismatches
        esp = tel.find("execute")
        assert "comm_payload_bytes" in esp.attrs

    def test_solver_counts_residual_only_when_it_runs(self):
        """Review finding (ISSUE 14): the solver's invert() never runs
        the ring/SUMMA verification, so its per-launch counters must
        not report phase=residual traffic — residual() counts its own
        section when it really executes."""
        from tpu_jordan.models import JordanSolver

        def residual_msgs():
            snap = REGISTRY.snapshot().get(
                "tpu_jordan_comm_messages_total", {})
            return sum(s.get("value", 0.0)
                       for s in snap.get("series", [])
                       if dict(s["labels"]).get("phase") == "residual")

        tel = Telemetry()
        sol = JordanSolver(n=46, block_size=8, workers=2,
                           engine="inplace", telemetry=tel)
        a = generate("absdiff", (46, 46), jnp.float32)
        before = residual_msgs()
        inv, sing = sol.invert(a)
        assert residual_msgs() == before     # invert: no residual ran
        sol.residual(a, inv)
        ran = [s for s in sol.comm.sigs if s.section == "residual"
               and not s.implicit]
        assert residual_msgs() == before + sum(s.executed for s in ran)


class TestDrift:
    def test_forced_judgment_records_event(self):
        """judge="always" with a tight band on a CPU mesh: the
        measured residue is nowhere near a v5e ICI projection, so the
        drift MUST be recorded — event + counter."""
        before = _counter_total("tpu_jordan_comm_drift_total")
        mark = RECORDER.total
        with comm.set_drift_policy(tolerance=1.5, judge="always"):
            res = solve(34, 8, workers=2, engine="inplace")
        d = res.comm.drift
        assert d["judged"] and d["out_of_band"] and d["event_recorded"]
        assert (_counter_total("tpu_jordan_comm_drift_total")
                - before) == 1
        evs = [e for e in RECORDER.since(mark)
               if e["kind"] == "comm_drift"]
        assert len(evs) == 1
        assert evs[0]["ratio"] == d["comm_vs_projected"]

    def test_auto_policy_never_judges_cpu(self):
        """The default policy on a CPU backend records the honest
        ratio UNJUDGED (the v5e constants off-chip are a cost-ranking
        stand-in) — no event spam from every distributed test."""
        mark = RECORDER.total
        res = solve(38, 8, workers=2, engine="inplace")
        d = res.comm.drift
        assert d["judged"] is False and d["event_recorded"] is False
        assert not [e for e in RECORDER.since(mark)
                    if e["kind"] == "comm_drift"]

    def test_never_policy_overrides(self):
        with comm.set_drift_policy(judge="never"):
            res = solve(42, 8, workers=2, engine="inplace")
        assert res.comm.drift["judged"] is False

    def test_bad_judge_value_raises(self):
        with pytest.raises(ValueError):
            with comm.set_drift_policy(judge="sometimes"):
                pass


class TestCostFeedback:
    def test_default_scale_is_identity(self):
        comm.reset_calibration()
        assert comm.cost_comm_scale() == 1.0

    def test_feedback_reprices_comm_term_only(self):
        """ROADMAP item 5's first rung: with feedback enabled, a
        measured 4x comm ratio re-prices a comm-dominated distributed
        point; with it off the ranking is byte-identical."""
        from tpu_jordan.tuning.registry import (TunePoint,
                                                projected_seconds)

        pt = TunePoint.create(8192, 256, workers=8, chip="v5e")
        single = TunePoint.create(8192, 256, workers=1, chip="v5e")
        comm.reset_calibration()
        base = projected_seconds(pt)
        base_single = projected_seconds(single)
        try:
            comm._record_calibration(4.0)
            assert projected_seconds(pt) == base  # feedback still off
            comm.set_cost_feedback(True)
            assert projected_seconds(pt) > base   # comm term re-priced
            # A single-chip point's comm term is launch-latency dust
            # (comm_model charges 3 scalar latencies per step even at
            # P=1): re-pricing moves it < 1%, vs the real comm share
            # of the distributed point.
            assert projected_seconds(single) == pytest.approx(
                base_single, rel=2e-2)
            assert (projected_seconds(pt) / base
                    > projected_seconds(single) / base_single)
        finally:
            comm.reset_calibration()
        assert projected_seconds(pt) == base


class TestWarmPathPins:
    @pytest.mark.smoke
    def test_warm_serve_zero_compile_with_recording_on(self):
        """ISSUE 14 acceptance: the warm-serve zero-compile /
        zero-measurement pins hold WITH collective recording enabled —
        the shims only act at trace time, and a warm executable never
        re-traces."""
        from tpu_jordan.serve import JordanService

        rng = np.random.default_rng(3)
        with JordanService(batch_cap=4, max_queue=64) as svc:
            svc.warmup(shapes=[16])
            compiles = _counter_total("tpu_jordan_compiles_total")
            measures = _counter_total(
                "tpu_jordan_tuner_measurements_total")
            with comm.recording():
                futs = [svc.submit(
                    2.0 * np.eye(16, dtype=np.float32)
                    + 0.1 * rng.standard_normal((16, 16)).astype(
                        np.float32))
                    for _ in range(12)]
                results = [f.result(timeout=120) for f in futs]
            assert len(results) == 12
            assert not any(r.singular for r in results)
            assert _counter_total(
                "tpu_jordan_compiles_total") == compiles
            assert _counter_total(
                "tpu_jordan_tuner_measurements_total") == measures


# ---------------------------------------------------------------------
# The demo + checker, both ways.
# ---------------------------------------------------------------------


@pytest.fixture(scope="module")
def demo_report():
    """One cached comm_demo run (inline — this process already hosts 8
    virtual devices) shared by every checker test below.  n=32 (the
    smallest size every leg's layout admits at Nr=4) keeps the nine-leg fixture inside the tier-1 budget; the
    CLI/`make comm-demo` gate still runs the n=48 default."""
    return comm.comm_demo(n=30, block_size=8)


class TestDemoAndChecker:
    @pytest.mark.slow  # tier-1 budget: the checker + demo fixture legs pin dtype threading nightly-fast
    def test_demo_dtype_and_generator_are_honored(self):
        """Review finding (ISSUE 14): --dtype/--generator thread into
        the demo legs (byte figures scale with dtype width — a float64
        request must reconcile float64 inventories, never silently
        float32), and complex is a typed refusal (the distributed
        engines are real-dtype)."""
        from tpu_jordan.driver import UsageError

        leg = comm._demo_leg("f64_probe", n=52, m=8, workers=2,
                             engine="inplace", gather=True,
                             dtype=jnp.float64, generator="rand")
        assert leg["comm"]["dtype"] == "float64"
        assert leg["comm"]["reconciled"] is True
        with pytest.raises(UsageError):
            comm.comm_demo(n=48, block_size=8, dtype="complex64")

    def test_demo_report_is_clean(self, demo_report):
        assert demo_report["silent_comm"] is False
        assert demo_report["ragged"] is True
        assert len(demo_report["legs"]) >= 4
        assert demo_report["drift_events"] >= 1

    def test_checker_accepts_real_report(self, demo_report, tmp_path):
        errs, silent = check_comm.check(demo_report)
        assert errs == [] and silent == []
        p = tmp_path / "comm.json"
        p.write_text(json.dumps(demo_report))
        assert check_comm.main([str(p)]) == 0

    def test_checker_rejects_stripped_collective(self, demo_report):
        """Doctored: one observed collective record deleted from a
        reconciliation leg — the checker re-derives the multiset and
        exit-2s (stripped/phantom), never trusting the flag."""
        doc = json.loads(json.dumps(demo_report))
        leg = doc["legs"][0]
        obs = leg["comm"]["observed"]["engine"]
        victim = next(e for e in obs if e["kind"] == "psum")
        victim["count"] -= 1
        errs, silent = check_comm.check(doc)
        assert any("stripped" in s or "phantom" in s for s in silent)

    def test_checker_rejects_unaccounted_collective(self, demo_report):
        doc = json.loads(json.dumps(demo_report))
        obs = doc["legs"][1]["comm"]["observed"]["engine"]
        obs.append({"kind": "psum", "axis": "p", "shape": [512, 512],
                    "dtype": "float32", "count": 2})
        errs, silent = check_comm.check(doc)
        assert any("UNACCOUNTED" in s for s in silent)

    def test_checker_rejects_forged_drift(self, demo_report):
        """Doctored: the out-of-band drift's recorder evidence is
        scrubbed (events stripped from the blackbox slice,
        event_recorded forged) — a silent drift, exit 2."""
        doc = json.loads(json.dumps(demo_report))
        doc["blackbox"]["events"] = [
            e for e in doc["blackbox"]["events"]
            if e.get("kind") != "comm_drift"]
        doc["drift_events"] = 0
        doc["drift_leg"]["comm"]["drift"]["event_recorded"] = False
        errs, silent = check_comm.check(doc)
        assert any("SILENT DRIFT" in s for s in silent)

    def test_checker_rejects_totals_lie(self, demo_report, tmp_path):
        doc = json.loads(json.dumps(demo_report))
        doc["legs"][0]["comm"]["totals"]["payload_bytes"] += 1024
        errs, silent = check_comm.check(doc)
        assert any("payload_bytes" in e for e in errs)
        p = tmp_path / "bad.json"
        p.write_text(json.dumps(doc))
        assert check_comm.main([str(p)]) == 1

    def test_checker_exit_codes(self, demo_report, tmp_path):
        doc = json.loads(json.dumps(demo_report))
        obs = doc["legs"][0]["comm"]["observed"]["engine"]
        obs[0]["count"] += 3
        p = tmp_path / "doctored.json"
        p.write_text(json.dumps(doc))
        assert check_comm.main([str(p)]) == 2
        q = tmp_path / "not_json.json"
        q.write_text("{nope")
        assert check_comm.main([str(q)]) == 1
