"""Tests for the solve driver, file I/O, and CLI (driver.py, io.py,
__main__.py) — the reference's end-to-end contract (main.cpp:65-93,
343-519): exit codes, file-error paths, singular-matrix path, residual.
"""

import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan import SingularMatrixError, solve
from tpu_jordan.io import MatrixReadError, read_matrix_file, write_matrix_file


class TestIO:
    def test_roundtrip(self, rng, tmp_path):
        a = rng.standard_normal((12, 12))
        path = str(tmp_path / "m.txt")
        write_matrix_file(path, a)
        b = read_matrix_file(path, 12)
        np.testing.assert_allclose(b, a, rtol=1e-15)

    def test_missing_file(self, tmp_path):
        # Reference -1 "cannot open" (main.cpp:231-237, 390-392).
        with pytest.raises(FileNotFoundError):
            read_matrix_file(str(tmp_path / "nope.txt"), 4)

    def test_short_file(self, tmp_path):
        # Reference -2 "cannot read" (main.cpp:255, 277, 393-394).
        path = tmp_path / "short.txt"
        path.write_text("1.0 2.0 3.0")
        with pytest.raises(MatrixReadError):
            read_matrix_file(str(path), 4)

    def test_garbage_file(self, tmp_path):
        path = tmp_path / "bad.txt"
        path.write_text("hello world this is not a matrix")
        with pytest.raises(MatrixReadError):
            read_matrix_file(str(path), 2)


class TestSolve:
    def test_generator_solve(self):
        res = solve(64, 16, dtype=jnp.float64)
        assert res.residual < 1e-9
        assert res.elapsed > 0
        assert res.gflops > 0

    def test_file_solve(self, rng, tmp_path):
        a = rng.standard_normal((16, 16))
        path = str(tmp_path / "a.txt")
        write_matrix_file(path, a)
        res = solve(16, 4, file=path, dtype=jnp.float64)
        np.testing.assert_allclose(
            np.asarray(res.inverse), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )
        assert res.residual < 1e-10

    def test_singular_raises(self, tmp_path):
        path = str(tmp_path / "sing.txt")
        write_matrix_file(path, np.ones((8, 8)))
        with pytest.raises(SingularMatrixError):
            solve(8, 4, file=path, dtype=jnp.float64)

    def test_refine_improves_f32(self):
        raw = solve(128, 32, dtype=jnp.float32)
        ref = solve(128, 32, dtype=jnp.float32, refine=2)
        assert ref.residual < raw.residual / 10

    @pytest.mark.slow  # tier-1 budget: the smoke 1D p2 solve bit-match
    # (test_solve_dist) and the dryrun-mirror legs (test_scale_demo) keep
    # fast-run distributed-solve coverage
    def test_distributed_solve(self):
        # workers=8 -> sharded path + ring-GEMM residual, the analog of
        # mpirun -np 8 (SURVEY.md §4).
        res = solve(64, 8, dtype=jnp.float64, workers=8)
        assert res.residual < 1e-9

    @pytest.mark.slow  # tier-1 budget: the engine-level 1D parity pins in
    # test_sharded_inplace and the driver-level dryrun bitmatch legs in
    # test_scale_demo keep the fast-run distributed-vs-single coverage
    def test_distributed_matches_single(self, rng, tmp_path):
        a = rng.standard_normal((32, 32))
        path = str(tmp_path / "a.txt")
        write_matrix_file(path, a)
        one = solve(32, 8, file=path, dtype=jnp.float64)
        eight = solve(32, 8, file=path, dtype=jnp.float64, workers=8)
        np.testing.assert_allclose(
            np.asarray(eight.inverse), np.asarray(one.inverse),
            rtol=1e-9, atol=1e-9,
        )


def run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tpu_jordan", *args],
        capture_output=True, text=True, timeout=300,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "HOME": "/root", "PYTHONPATH": "/root/repo"},
    )


class TestCLI:
    def test_usage_exit_1(self):
        # Bad args -> usage + exit 1 (main.cpp:77-85).
        r = run_cli("0", "0")
        assert r.returncode == 1
        assert "usage" in r.stderr + r.stdout

    def test_missing_args_exit_1(self):
        r = run_cli("64")
        assert r.returncode == 1

    def test_success_exit_0(self):
        r = run_cli("64", "16", "--quiet")
        assert r.returncode == 0, r.stderr
        assert "glob_time:" in r.stdout
        assert "residual:" in r.stdout

    def test_file_not_found_exit_2(self):
        # solve failure -> exit 2 (main.cpp:86-90).
        r = run_cli("8", "4", "/does/not/exist.txt")
        assert r.returncode == 2
        assert "cannot open" in r.stdout

    def test_singular_exit_2(self, tmp_path):
        path = tmp_path / "sing.txt"
        write_matrix_file(str(path), np.zeros((4, 4)))
        r = run_cli("4", "4", str(path), "--dtype", "float64")
        assert r.returncode == 2
        assert "singular matrix" in r.stdout

    def test_float16_exit_0(self):
        # fp16 storage dtype is a first-class CLI surface (config.py has its
        # EPS); computes in fp32 and rounds once, like bfloat16.
        from tpu_jordan.__main__ import main

        assert main(["32", "8", "--dtype", "float16", "--quiet"]) == 0

    def test_no_gather_single_device_exit_1(self):
        # gather=False requires a distributed generator run -> usage error.
        from tpu_jordan.__main__ import main

        assert main(["32", "8", "--no-gather", "--quiet"]) == 1

    def test_solve_reports_kappa(self):
        # κ∞(A) = ‖A‖∞‖X‖∞ on paths holding full A and X; matches numpy.
        res = solve(32, 8, dtype=jnp.float64)
        from tpu_jordan.ops import generate

        a = np.asarray(generate("absdiff", (32, 32), jnp.float64))
        want = np.linalg.cond(a, np.inf)
        np.testing.assert_allclose(res.kappa, want, rtol=1e-6)
        np.testing.assert_allclose(
            res.rel_residual, res.residual / np.linalg.norm(a, np.inf),
            rtol=1e-12)
        # Distributed refine path carries it too; since round 5 the
        # non-refine distributed branches report it as well, from
        # block-sharded row sums (TestDistributedKappa pins the values).
        res2 = solve(64, 8, workers=4, dtype=jnp.float32, refine=1)
        assert res2.kappa is not None and res2.kappa > 1
        res3 = solve(64, 8, workers=4, dtype=jnp.float32)
        assert res3.kappa is not None and res3.rel_residual is not None

    def test_sleep_flag_prints_pid_and_delays(self, capsys):
        # The reference's -DSLEEP attach-a-debugger hook (main.cpp:8,70-72).
        import os
        import time

        from tpu_jordan.__main__ import main

        t0 = time.perf_counter()
        assert main(["16", "8", "--sleep", "1", "--quiet"]) == 0
        assert time.perf_counter() - t0 >= 1.0
        assert f"pid {os.getpid()} sleeping 1s" in capsys.readouterr().out

    def test_no_gather_distributed_exit_0(self):
        from tpu_jordan.__main__ import main

        assert main(["64", "8", "--workers", "4", "--no-gather",
                     "--quiet"]) == 0


class TestEngineSelection:
    """The engine/group product surface (VERDICT r4 #4): solve(), the
    CLI, and JordanSolver share driver.resolve_engine."""

    def test_resolve_engine_contract(self):
        from tpu_jordan.driver import UsageError, resolve_engine

        assert resolve_engine("auto", 0) == ("auto", 0)
        assert resolve_engine("grouped", 0) == ("grouped", 2)
        assert resolve_engine("grouped", 4) == ("grouped", 4)
        assert resolve_engine("auto", 3) == ("grouped", 3)
        assert resolve_engine("inplace", 0) == ("inplace", 0)
        assert resolve_engine("augmented", 0) == ("augmented", 0)
        # Only 0 means "unset": an explicit group=1 is rejected
        # everywhere rather than silently coerced (it IS the plain
        # engine; running anything else under that label misreports).
        for bad in (("nope", 0), ("inplace", 2), ("augmented", 2),
                    ("auto", -1), ("grouped", 1), ("auto", 1),
                    ("augmented", 1)):
            with pytest.raises(UsageError):
                resolve_engine(*bad)

    @pytest.mark.parametrize("engine,workers", [
        ("grouped", 1),
        # tier-1 budget: distributed-grouped runs nightly; ("grouped", 1)
        # + ("inplace", 4) keep the engine and the 1D mesh fast-run legs.
        pytest.param("grouped", 4, marks=pytest.mark.slow),
        pytest.param("grouped", (2, 2), marks=pytest.mark.slow),
        ("augmented", 1), ("inplace", 4),
    ])
    def test_engines_solve_and_verify(self, engine, workers):
        r = solve(64, 8, workers=workers, dtype=jnp.float64, engine=engine)
        assert r.residual < 1e-9 * 64 * 63   # |i-j| norm-scaled bound

    @pytest.mark.slow  # tier-1 budget: registry-ranking + smoke grouped parity stay
    def test_grouped_matches_auto_to_rounding(self):
        r_a = solve(64, 8, dtype=jnp.float64)
        r_g = solve(64, 8, dtype=jnp.float64, engine="grouped")
        np.testing.assert_allclose(np.asarray(r_g.inverse),
                                   np.asarray(r_a.inverse),
                                   rtol=1e-9, atol=1e-9)

    @pytest.mark.slow   # tier-1 headroom (ISSUE 3): CLI engine surface
    #   stays tier-1 via the auto/tune CLI tests; grouped solves via
    #   test_engines_solve_and_verify
    def test_cli_engine_grouped_exit_0(self):
        from tpu_jordan.__main__ import main

        assert main(["64", "8", "--engine", "grouped", "--quiet"]) == 0
        assert main(["64", "8", "--group", "4", "--quiet"]) == 0

    def test_cli_engine_usage_errors(self):
        from tpu_jordan.__main__ import main

        # group on the inplace/augmented engines is a usage error (1).
        assert main(["64", "8", "--engine", "inplace", "--group", "2",
                     "--quiet"]) == 1
        assert main(["64", "8", "--engine", "augmented", "--group", "2",
                     "--quiet"]) == 1
        # batch with engine/group: the batched engine is its own path.
        assert main(["32", "8", "--batch", "2", "--engine", "grouped",
                     "--quiet"]) == 1

    @pytest.mark.slow   # tier-1 headroom (ISSUE 3): grouped stays
    #   tier-1 via solve-level auto/grouped parity and the engine
    #   suites; the JordanSolver wrapper runs nightly
    def test_solver_grouped_engine(self, rng):
        from tpu_jordan.models import JordanSolver

        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        s = JordanSolver(64, 8, dtype=jnp.float64, engine="grouped")
        assert s.group == 2
        inv, sing = s.invert(a)
        assert not bool(sing)
        from tpu_jordan.ops.jordan_inplace import (
            block_jordan_invert_inplace_grouped,
        )

        want, _ = block_jordan_invert_inplace_grouped(a, block_size=8,
                                                      group=2)
        np.testing.assert_allclose(np.asarray(inv), np.asarray(want),
                                   rtol=1e-12, atol=1e-12)

    @pytest.mark.slow   # tier-1 headroom (ISSUE 3): grouped-distributed
    #   parity stays tier-1 in the parallel suites; JordanSolver grouped
    #   single-device stays above
    def test_solver_grouped_distributed(self, rng):
        from tpu_jordan.models import JordanSolver

        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float64)
        s = JordanSolver(64, 8, dtype=jnp.float64, workers=4,
                         engine="grouped", group=4)
        inv, sing = s.invert(a)
        assert not bool(sing)
        res = np.max(np.abs(np.asarray(a) @ np.asarray(inv) - np.eye(64)))
        assert res < 1e-9


class TestDistributedKappa:
    """κ∞/rel_residual populated on EVERY distributed branch (VERDICT r4
    #6) — from block-sharded row sums, no n×n materialization."""

    @pytest.mark.parametrize("workers,gather", [
        (4, True), (4, False),
        # tier-1 headroom (ISSUE 3): 2D κ∞ gather=False stays tier-1;
        # the gathered 2D leg duplicates it through the same
        # inf_norm_blocks path and runs nightly.
        pytest.param((2, 2), True, marks=pytest.mark.slow),
        ((2, 2), False),
    ])
    def test_kappa_populated(self, workers, gather):
        r = solve(64, 8, workers=workers, gather=gather,
                  dtype=jnp.float64)
        assert r.kappa is not None and r.rel_residual is not None
        from tpu_jordan.ops import generate

        a = np.asarray(generate("absdiff", (64, 64), jnp.float64))
        want = np.linalg.cond(a, np.inf)
        np.testing.assert_allclose(r.kappa, want, rtol=1e-6)
        assert r.rel_residual < 1e-12

    def test_kappa_ragged_padding_masked(self):
        # n=50 pads to N=56 (m=8, p=4): identity-pad rows (sum exactly 1)
        # must not leak into the norms.
        r = solve(50, 8, workers=4, gather=False, dtype=jnp.float64)
        from tpu_jordan.ops import generate

        a = np.asarray(generate("absdiff", (50, 50), jnp.float64))
        np.testing.assert_allclose(r.kappa, np.linalg.cond(a, np.inf),
                                   rtol=1e-6)


class TestNoGatherCorner:
    """gather=False verbose runs still print the inverse's corner
    (main.cpp:459-461 always shows it), assembled from the owning blocks
    without a global gather."""

    @pytest.mark.parametrize("workers", [4, (2, 2)])
    def test_corner_matches_gathered_inverse(self, workers):
        # m=8 < 10 so the printed corner spans two block rows/cols — the
        # multi-block assembly path, not just a single-block slice.
        ref = solve(64, 8, workers=workers, dtype=jnp.float64)
        res = solve(64, 8, workers=workers, dtype=jnp.float64,
                    gather=False)
        from tpu_jordan.driver import make_distributed_backend

        be = make_distributed_backend(workers, 64, 8)
        corner = np.asarray(be.corner(res.inverse_blocks, 64))
        assert corner.shape == (10, 10)
        np.testing.assert_allclose(corner, np.asarray(ref.inverse)[:10, :10],
                                   rtol=1e-12, atol=1e-12)

    def test_verbose_no_gather_prints_corner(self, capsys):
        solve(32, 8, workers=4, dtype=jnp.float64, gather=False,
              verbose=True)
        out = capsys.readouterr().out
        assert "inverse matrix:" in out
        # ten tab-separated "%.2f" rows follow, like the reference print.
        rows = [ln for ln in out.splitlines() if ln.count("\t") >= 9]
        assert len(rows) >= 10


class TestSolveBatch:
    def test_batch_solve_rand_distinct(self):
        import numpy as np

        from tpu_jordan.driver import solve_batch

        res = solve_batch(32, 8, batch=3, generator="rand")
        assert res.inverse.shape == (3, 32, 32)
        # rand elements are distinct matrices (per-element offsets).
        assert not np.allclose(np.asarray(res.inverse[0]),
                               np.asarray(res.inverse[1]))
        assert res.residual / 16 < 5e-3
        assert res.gflops > 0

    def test_cli_batch_flag(self):
        from tpu_jordan.__main__ import main

        assert main(["32", "8", "--batch", "3", "--quiet",
                     "--generator", "rand"]) == 0

    def test_cli_batch_with_file_is_usage_error(self, tmp_path):
        import numpy as np

        from tpu_jordan.__main__ import main
        from tpu_jordan.io import write_matrix_file

        p = str(tmp_path / "m.txt")
        write_matrix_file(p, np.eye(8))
        assert main(["8", "4", p, "--batch", "2", "--quiet"]) == 1

    def test_cli_batch_with_workers_is_usage_error(self):
        from tpu_jordan.__main__ import main

        assert main(["32", "8", "--batch", "2", "--workers", "4",
                     "--quiet"]) == 1

    def test_cli_batch_with_no_gather_is_usage_error(self):
        # --no-gather has no meaning for the (single-device, gathered)
        # batch path: reject like every other invalid flag combination.
        from tpu_jordan.__main__ import main

        assert main(["32", "8", "--batch", "2", "--no-gather",
                     "--quiet"]) == 1
