"""ISSUE 17 — the LP/QP optimization-driver subsystem (tpu_jordan/lpqp).

The contract under test, per the ISSUE's coverage satellite:

  * the seeded instance generators carry EXACT optimality certificates
    (the constructed x*/y* zero the KKT residual) and are
    deterministic like every other fixture;
  * a tiny LP round-trips through a warmed fleet — one
    ``invert(resident=True)`` + a rank-1 update per pivot + periodic
    verification solves — converging under the solver's OWN eps·n·κ
    gate with ZERO compiles after warmup (smoke tier);
  * a zero drift budget routes EVERY update through the ``re_invert``
    rung and the driver still converges, with the journey/recorder
    causality pinned (each rung's recorded breadcrumb is preceded by
    its drift-budget gate-failure event);
  * a seeded ``replica_kill`` mid-optimization leaves the per-iterate
    outcome stream and the final solution fingerprint BIT-IDENTICAL to
    the fault-free replay;
  * the batched update lane (ISSUE 17 tentpole part 3) fuses riders to
    distinct handles into one vmapped launch (occupancy > 1, per-rider
    verified results) and refuses mixed-bucket/dtype riders with the
    typed ``MixedUpdateBatchError`` — batch-mates untouched;
  * ``lp_demo``'s report validates clean through tools/check_lp.py and
    doctored-silent variants exit 2 (the both-ways checker
    discipline); misapplied ``--lp-demo`` CLI flags are typed
    UsageErrors (exit 1).

Heavy parametrizations are slow-marked with named fast siblings
(``test_lp_heavy_families_slow`` ↔ ``test_lp_ill_converges``,
``test_replica_kill_bitmatch_heavy_slow`` ↔
``test_replica_kill_bitmatches_fault_free``) so tier-1 stays inside
its budget.
"""

import copy
import importlib.util
import json
import pathlib
from concurrent.futures import Future

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.fleet import JordanFleet
from tpu_jordan.lpqp import (OptimizeError, lp_instance, lp_kkt_residual,
                             qp_instance, qp_kkt_residual, solve_lp,
                             solve_qp)
from tpu_jordan.obs.metrics import REGISTRY
from tpu_jordan.resilience import FaultPlan, ResiliencePolicy
from tpu_jordan.resilience import activate as _activate
from tpu_jordan.resilience.policy import RetryPolicy

_repo = pathlib.Path(__file__).resolve().parent.parent


def _fleet(replicas=2, **kw):
    """A small, fast LP/QP-shaped fleet: float64 (the drivers' pricing
    tolerances assume it), cap-1 lanes, short stabilization."""
    kw.setdefault("engine", "auto")
    kw.setdefault("dtype", jnp.float64)
    kw.setdefault("batch_cap", 1)
    kw.setdefault("max_wait_ms", 0.5)
    kw.setdefault("stable_after_s", 0.2)
    kw.setdefault("liveness_deadline_s", 5.0)
    kw.setdefault("policy", ResiliencePolicy(
        retry=RetryPolicy(max_retries=4, backoff_s=0.0)))
    return JordanFleet(replicas=replicas, **kw)


def _warm(fleet, n, ks=(1,)):
    """Warm the driver's lanes; LP needs only the rank-1 update lane,
    QP's bound toggles ride rank-2 as well (ks=(1, 2))."""
    fleet.warmup([n], update_shapes=[(n, k) for k in ks],
                 solve_shapes=[(n, 1)])


def _compiles():
    return REGISTRY.counter("tpu_jordan_compiles_total").total()


def _assert_accounted(rep):
    assert sum(rep.ledger.values()) == rep.updates
    for r in rep.iterates:
        if "solve_rel" in r:
            assert r["solve_pass"], r
            assert r["agree"], r


class TestProblemFixtures:
    def test_lp_certificate_exact(self):
        """The constructed vertex IS the optimum: the dual certificate
        y recovered from the optimal (G) basis zeroes the KKT residual
        to rounding."""
        for cond, tol in (("well", 1e-12), ("ill", 1e-9)):
            prob = lp_instance(m=8, seed=3, cond=cond)
            g = prob.a[:, :prob.m]
            y = np.linalg.solve(g.T, prob.c[:prob.m])
            assert lp_kkt_residual(prob, prob.x_star, y) < tol
            assert np.all(prob.b > 0)            # slack start feasible
            assert prob.basis0 == tuple(range(prob.m, prob.n))

    def test_qp_certificate_exact(self):
        for cond in ("well", "ill"):
            prob = qp_instance(n=10, seed=3, cond=cond)
            assert qp_kkt_residual(prob, prob.x_star) < 1e-12
            # SPD by construction.
            assert np.linalg.eigvalsh(prob.q).min() > 0

    def test_deterministic_and_seed_sensitive(self):
        a = lp_instance(m=6, seed=9, cond="ill")
        b = lp_instance(m=6, seed=9, cond="ill")
        assert a.a.tobytes() == b.a.tobytes()
        assert a.c.tobytes() == b.c.tobytes()
        assert a.name == b.name
        c = lp_instance(m=6, seed=10, cond="ill")
        assert a.a.tobytes() != c.a.tobytes()
        qa = qp_instance(n=6, seed=9)
        qb = qp_instance(n=6, seed=9)
        assert qa.q.tobytes() == qb.q.tobytes()

    def test_validation_typed(self):
        with pytest.raises(ValueError):
            lp_instance(m=8, cond="medium")
        with pytest.raises(ValueError):
            lp_instance(m=1)
        with pytest.raises(ValueError):
            qp_instance(n=1)


class TestLpDriver:
    @pytest.mark.smoke   # the LP round-trip through the fleet (smoke)
    def test_lp_round_trip_smoke(self):
        """Tiny LP through a warmed 2-replica fleet: converges under
        the solver's own gate, zero compiles after warmup, every
        update accounted, objective at the constructed optimum."""
        n = 8
        prob = lp_instance(m=n, seed=0, cond="well")
        with _fleet() as fleet:
            _warm(fleet, n)
            c0 = _compiles()
            rep = solve_lp(prob, fleet)
            assert _compiles() == c0          # zero compiles after warmup
            ledger = fleet.stats()["ledger"]
        assert rep.converged
        assert rep.kkt_rel_final <= rep.kkt_threshold
        assert rep.updates > 0 and rep.solves > 0
        _assert_accounted(rep)
        assert abs(rep.objective - prob.obj_star) <= (
            1e-8 * (1.0 + abs(prob.obj_star)))
        assert ledger["outstanding"] == 0

    @pytest.mark.slow  # tier-1 budget: the demo+checker test runs (and
    # convergence-gates) the LP-ill leg in every fast run
    def test_lp_ill_converges(self):
        """Fast sibling of ``test_lp_heavy_families_slow``: the
        ill-conditioned family at m=8 converges through the same
        fleet path."""
        prob = lp_instance(m=8, seed=0, cond="ill")
        with _fleet() as fleet:
            _warm(fleet, 8)
            rep = solve_lp(prob, fleet)
        assert rep.converged
        _assert_accounted(rep)

    @pytest.mark.slow  # heavy parametrization; fast sibling: test_lp_ill_converges
    @pytest.mark.parametrize("m,cond", [(24, "well"), (24, "ill")])
    def test_lp_heavy_families_slow(self, m, cond):
        prob = lp_instance(m=m, seed=1, cond=cond)
        with _fleet() as fleet:
            _warm(fleet, m)
            rep = solve_lp(prob, fleet, solve_every=4)
        assert rep.converged
        _assert_accounted(rep)
        assert abs(rep.objective - prob.obj_star) <= (
            1e-7 * (1.0 + abs(prob.obj_star)))

    def test_iteration_cap_typed_with_report(self):
        prob = lp_instance(m=8, seed=0, cond="well")
        with _fleet() as fleet:
            _warm(fleet, 8)
            with pytest.raises(OptimizeError) as ei:
                solve_lp(prob, fleet, max_iters=1)
        rep = ei.value.report
        assert rep is not None and not rep.converged
        assert rep.iterations == 1 and len(rep.iterates) == 1


class TestQpDriver:
    @pytest.mark.slow  # tier-1 budget: the demo+checker test convergence-
    # gates the QP well/ill legs in every fast run
    def test_qp_round_trip(self):
        n = 8
        prob = qp_instance(n=n, seed=0, cond="well")
        with _fleet() as fleet:
            _warm(fleet, n, ks=(1, 2))
            c0 = _compiles()
            rep = solve_qp(prob, fleet)
            assert _compiles() == c0
        assert rep.converged
        assert rep.updates > 0            # rank-2 toggles rode the lane
        _assert_accounted(rep)
        assert np.max(np.abs(rep.x - prob.x_star)) < 1e-6
        assert abs(rep.objective - prob.obj_star) <= (
            1e-8 * (1.0 + abs(prob.obj_star)))

    @pytest.mark.slow  # the ill QP family also runs inside the demo-checker test's lp_demo legs; fast sibling: test_qp_round_trip
    def test_qp_ill_converges(self):
        prob = qp_instance(n=8, seed=0, cond="ill")
        with _fleet() as fleet:
            _warm(fleet, 8, ks=(1, 2))
            rep = solve_qp(prob, fleet)
        assert rep.converged
        _assert_accounted(rep)


class TestDriftCausality:
    def test_zero_budget_re_inverts_with_causality(self):
        """Drift-budget crossing mid-loop (ISSUE 17 satellite): with a
        ZERO budget every update trips ``re_invert``, the driver still
        converges on the recovered inverses, and the flight recorder
        pins the causality — each ``recovery_rung`` breadcrumb is
        preceded (by seq) by its own drift-budget
        ``residual_gate_failure``, and the journeys carry the
        ``re_inverted`` outcome hop."""
        from tpu_jordan.obs.recorder import RECORDER

        n = 8
        prob = lp_instance(m=n, seed=0, cond="well")
        rungs = REGISTRY.counter("tpu_jordan_recovery_rungs_total")
        with _fleet(update_drift_budget_factor=0.0) as fleet:
            _warm(fleet, n)
            mark = RECORDER.total
            r0 = rungs.total()
            rep = solve_lp(prob, fleet)
        assert rep.converged
        assert rep.ledger["re_inverted"] == rep.updates > 0
        assert rep.ledger["refreshed"] == 0
        assert rungs.total() - r0 == rep.updates
        events = RECORDER.since(mark)
        gate_seqs = sorted(
            e["seq"] for e in events
            if e["kind"] == "residual_gate_failure"
            and e.get("workload") == "update"
            and e.get("cause") == "drift_budget")
        rung_seqs = sorted(
            e["seq"] for e in events
            if e["kind"] == "recovery_rung"
            and e.get("rung") == "re_invert"
            and e.get("workload") == "update")
        assert len(rung_seqs) == rep.updates
        assert len(gate_seqs) == rep.updates
        # Causality: the i-th rung is preceded by the i-th crossing.
        assert all(g < r for g, r in zip(gate_seqs, rung_seqs))
        hops = [e for e in events if e["kind"] == "journey"
                and e.get("event") == "update"]
        assert sum(e.get("outcome") == "re_inverted"
                   for e in hops) == rep.updates


class TestChaosBitmatch:
    def _run(self, prob, plan=None, replicas=3, kills_expected=0):
        faults = REGISTRY.counter("tpu_jordan_faults_injected_total")
        f0 = faults.total()
        with _fleet(replicas=replicas,
                    policy=ResiliencePolicy(retry=RetryPolicy(
                        max_retries=6, backoff_s=0.0))) as fleet:
            _warm(fleet, prob.m)
            if plan is not None:
                with _activate(plan):
                    rep = solve_lp(prob, fleet)
            else:
                rep = solve_lp(prob, fleet)
        assert faults.total() - f0 >= kills_expected
        return rep

    @pytest.mark.slow  # tier-1 budget: the fleet-level seeded replica-kill
    # bit-match (test_fleet.py) keeps the fast-run chaos-determinism pin;
    # the lp-demo gate replays this leg end-to-end
    def test_replica_kill_bitmatches_fault_free(self):
        """Fast sibling of ``test_replica_kill_bitmatch_heavy_slow``:
        one seeded kill mid-optimization; outcome stream + final
        fingerprint bit-match the fault-free replay."""
        n = 8
        prob = lp_instance(m=n, seed=0, cond="ill")
        base = self._run(prob)
        plan = FaultPlan.seeded(
            0, points={"replica_kill": (1, max(3, 2 * n))})
        chaos = self._run(prob, plan=plan, kills_expected=1)
        assert base.converged and chaos.converged
        tok = lambda rep: [(r.get("outcome"), r.get("version"),  # noqa: E731
                            r["kkt_hex"]) for r in rep.iterates]
        assert tok(base) == tok(chaos)
        assert base.fingerprint == chaos.fingerprint != ""

    @pytest.mark.slow  # heavy chaos parametrization; fast sibling: test_replica_kill_bitmatches_fault_free
    def test_replica_kill_bitmatch_heavy_slow(self):
        n = 16
        prob = lp_instance(m=n, seed=2, cond="ill")
        base = self._run(prob)
        plan = FaultPlan.seeded(
            2, points={"replica_kill": (2, max(3, 2 * n))})
        chaos = self._run(prob, plan=plan, kills_expected=2)
        assert base.fingerprint == chaos.fingerprint != ""
        assert len(base.iterates) == len(chaos.iterates)


class TestBatchedUpdateLane:
    def test_fused_launch_occupancy_and_parity(self):
        """Riders to DISTINCT handles share one vmapped launch
        (occupancy > 1), each re-verified in-launch; results match the
        fresh inverse of each mutated matrix; warm pin holds."""
        from tpu_jordan.serve.service import JordanService

        n, cap = 16, 3
        rng = np.random.default_rng(5)
        mats = [(rng.standard_normal((n, n))
                 + n * np.eye(n)).astype(np.float32)
                for _ in range(cap)]
        muts = [(rng.standard_normal((n, 1)).astype(np.float32) * 0.1,
                 rng.standard_normal((n, 1)).astype(np.float32) * 0.1)
                for _ in range(cap)]
        with JordanService(batch_cap=cap, max_wait_ms=25.0,
                           dtype=jnp.float32) as svc:
            svc.warmup(update_shapes=[(n, 1)])
            refs = [svc.invert(a, resident=True, handle_id=f"h{i}",
                               timeout=120)
                    for i, a in enumerate(mats)]
            c0 = _compiles()
            futs = [svc.submit_update(ref, u, v)
                    for ref, (u, v) in zip(refs, muts)]
            res = [f.result(120) for f in futs]
            assert _compiles() == c0
        assert max(r.batch_occupancy for r in res) > 1
        for r, a, (u, v) in zip(res, mats, muts):
            assert r.update_outcome in ("refreshed", "re_inverted")
            assert not r.singular
            want = np.linalg.inv(a + u @ v.T)
            assert np.abs(np.asarray(r.inverse) - want).max() < 1e-3

    def test_mixed_rider_refused_typed_batchmates_untouched(self):
        """Direct batcher misuse — a rider whose padded factors do not
        match the lane's (bucket, k_bucket, dtype) — is refused with
        the typed MixedUpdateBatchError; the conforming batch-mate in
        the SAME batch still resolves."""
        import time

        from tpu_jordan.serve.batcher import (MixedUpdateBatchError,
                                              _Request)
        from tpu_jordan.serve.executors import bucket_for, k_bucket_for
        from tpu_jordan.serve.service import JordanService

        n = 16
        rng = np.random.default_rng(6)
        a = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(
            np.float32)
        u = rng.standard_normal((n, 1)).astype(np.float32) * 0.1
        v = rng.standard_normal((n, 1)).astype(np.float32) * 0.1
        with JordanService(batch_cap=2, max_wait_ms=5.0,
                           dtype=jnp.float32) as svc:
            svc.warmup(update_shapes=[(n, 1)])
            ref = svc.invert(a, resident=True, timeout=120)
            bucket, kb = bucket_for(n), k_bucket_for(1)
            pad = np.zeros((bucket, kb), np.float32)
            pu, pv = pad.copy(), pad.copy()
            pu[:n, :1], pv[:n, :1] = u, v
            now = time.perf_counter()

            def req(fu, fv):
                return _Request(
                    padded=None, n=n, bucket_n=bucket, t_enqueue=now,
                    future=Future(), workload="update", rhs=kb, k=1,
                    handle=ref, padded_u=fu, padded_v=fv)

            bad = req(pu.astype(np.float64), pv.astype(np.float64))
            good = req(pu, pv)
            svc._batcher._execute_updates(("update", bucket, kb),
                                          [bad, good], now)
            err = bad.future.exception(timeout=120)
            assert isinstance(err, MixedUpdateBatchError)
            assert isinstance(err, TypeError)    # typed, catchable
            res = good.future.result(timeout=120)
            assert res.update_outcome in ("refreshed", "re_inverted")
            want = np.linalg.inv(a + u @ v.T)
            assert np.abs(np.asarray(res.inverse) - want).max() < 1e-3


class TestLpDemoAndChecker:
    def test_demo_report_valid_and_doctored_exits(self, tmp_path):
        """Both-ways gate (the repo's checker discipline): a real
        small-scale lp_demo report validates clean through
        tools/check_lp.py, and doctored-silent variants — a residual
        bit mismatch, an unaccounted update, a diverged chaos
        fingerprint — each exit 2; a dead batched lane exits 1."""
        from tpu_jordan.lpqp.demo import lp_demo

        spec = importlib.util.spec_from_file_location(
            "check_lp", _repo / "tools" / "check_lp.py")
        check_lp = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(check_lp)

        report = lp_demo(n=8, replicas=2, kills=1, batch_cap=2)
        errs, stale = check_lp.check(report)
        assert errs == [] and stale == [], (errs, stale)
        assert not report["silent_divergence"]
        assert report["batched"]["occupancy"] > 1
        assert report["chaos"]["fingerprint_bitmatch"]

        def rc(rep, name):
            p = tmp_path / name
            p.write_text(json.dumps(rep))
            return check_lp.main([str(p)])

        assert rc(report, "ok.json") == 0
        d1 = copy.deepcopy(report)                 # doctored residual
        it = d1["legs"]["lp_well"]["iterates"][-1]
        it["kkt_hex"] = float(it["kkt_rel"] * 3.0).hex()
        assert rc(d1, "hex.json") == 2
        d2 = copy.deepcopy(report)                 # unaccounted update
        d2["legs"]["qp_well"]["ledger"]["refreshed"] += 1
        assert rc(d2, "ledger.json") == 2
        d3 = copy.deepcopy(report)                 # silent chaos drift
        d3["chaos"]["fingerprint_bitmatch"] = False
        assert rc(d3, "chaos.json") == 2
        d4 = copy.deepcopy(report)                 # lane never fused
        d4["batched"]["occupancy"] = 1
        assert rc(d4, "occ.json") == 1

    def test_cli_misapplied_flags_typed_exit_1(self):
        from tpu_jordan.__main__ import main

        base = ["16", "8", "--lp-demo", "--dtype", "float64", "--quiet"]
        assert main(base + ["--workers", "8"]) == 1
        assert main(base + ["--serve-requests", "32"]) == 1
        assert main(base + ["--batch", "4"]) == 1
        assert main(base + ["--engine", "jordan"]) == 1
        assert main(base + ["--workload", "solve"]) == 1
        assert main(base + ["--numerics", "summary"]) == 1
        assert main(base + ["--slo-report"]) == 1
        assert main(base + ["--scaling-floor", "2.0"]) == 1
        assert main(base + ["--replicas", "1"]) == 1
        assert main(base + ["--kills", "0"]) == 1
        assert main(base + ["--batch-cap", "1"]) == 1
        # Bland pricing needs f64 reduced costs: f32 refused typed.
        assert main(["16", "8", "--lp-demo", "--dtype", "float32",
                     "--quiet"]) == 1
