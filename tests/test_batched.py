"""Batched inversion (ops/batched.py) — the vmap capability beyond the
reference (BASELINE.md north star: batched Jordan solves)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.ops import batched_jordan_invert


class TestBatchedInvert:
    def test_stack_matches_linalg(self, rng):
        a = rng.standard_normal((6, 24, 24))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert inv.shape == (6, 24, 24)
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_nested_batch_dims(self, rng):
        a = rng.standard_normal((2, 3, 16, 16))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert inv.shape == (2, 3, 16, 16)
        assert sing.shape == (2, 3)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_per_element_singularity(self, rng):
        good = rng.standard_normal((8, 8))
        bad = np.ones((8, 8))
        a = jnp.asarray(np.stack([good, bad, good]))
        inv, sing = batched_jordan_invert(a, block_size=4)
        assert list(np.asarray(sing)) == [False, True, False]
        np.testing.assert_allclose(
            np.asarray(inv[0]), np.linalg.inv(good), rtol=1e-8, atol=1e-8
        )
