"""Batched inversion (ops/batched.py) — the vmap capability beyond the
reference (BASELINE.md north star: batched Jordan solves)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.ops import batched_jordan_invert


class TestBatchedInvert:
    def test_stack_matches_linalg(self, rng):
        a = rng.standard_normal((6, 24, 24))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert inv.shape == (6, 24, 24)
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_nested_batch_dims(self, rng):
        a = rng.standard_normal((2, 3, 16, 16))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert inv.shape == (2, 3, 16, 16)
        assert sing.shape == (2, 3)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_per_element_singularity(self, rng):
        good = rng.standard_normal((8, 8))
        bad = np.ones((8, 8))
        a = jnp.asarray(np.stack([good, bad, good]))
        inv, sing = batched_jordan_invert(a, block_size=4)
        assert list(np.asarray(sing)) == [False, True, False]
        np.testing.assert_allclose(
            np.asarray(inv[0]), np.linalg.inv(good), rtol=1e-8, atol=1e-8
        )

    def test_large_batch_routes_through_fori_engine(self, rng, monkeypatch):
        # Large B x many probe shapes is a measured-failing compile
        # region for the unrolled engine on TPU (PHASES.md "compile
        # lottery"); the dispatch must route big batches through the
        # fori engine (one probe shape), and results must agree.
        import tpu_jordan.ops.batched as batched_mod
        import tpu_jordan.ops.jordan_inplace as ji

        calls = []
        orig = ji.block_jordan_invert_inplace_fori

        def spy(x, **kw):
            calls.append(x.shape)
            return orig(x, **kw)

        monkeypatch.setattr(ji, "block_jordan_invert_inplace_fori", spy)
        # Nr = 48/8 = 6 > 4 and B*Nr = 132 >= 128 -> fori route.
        a = rng.standard_normal((22, 48, 48))
        inv, sing = batched_mod.batched_jordan_invert(
            jnp.asarray(a), block_size=8)
        assert calls, "fori engine was not selected for the large batch"
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-6, atol=1e-6
        )

    def test_inplace_engine_selected_and_agrees(self, rng, monkeypatch):
        # Nr <= MAX_UNROLL_NR must route through the vmapped in-place
        # engine (the 2x-flops win applies to batches too); its results
        # must match the augmented engine.
        import tpu_jordan.driver as driver_mod
        from tpu_jordan.ops.jordan_inplace import block_jordan_invert_inplace

        calls = []
        orig = driver_mod.single_device_invert

        def spy(n, m):
            engine = orig(n, m)
            calls.append(engine is block_jordan_invert_inplace)
            return engine

        monkeypatch.setattr(driver_mod, "single_device_invert", spy)
        a = rng.standard_normal((4, 32, 32))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert calls and all(calls), "in-place engine was not selected"
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_augmented_fallback_large_Nr(self, rng):
        # Nr > MAX_UNROLL_NR: the fori_loop engine takes over (no
        # unrolled-trace blowup for many tiny blocks).
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n, m = 2 * (MAX_UNROLL_NR + 2), 2
        assert -(-n // m) > MAX_UNROLL_NR
        a = rng.standard_normal((2, n, n))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=m)
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-6, atol=1e-6
        )
