"""Batched inversion (ops/batched.py) — the vmap capability beyond the
reference (BASELINE.md north star: batched Jordan solves)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.ops import batched_jordan_invert


class TestBatchedInvert:
    def test_stack_matches_linalg(self, rng):
        a = rng.standard_normal((6, 24, 24))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert inv.shape == (6, 24, 24)
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_nested_batch_dims(self, rng):
        a = rng.standard_normal((2, 3, 16, 16))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert inv.shape == (2, 3, 16, 16)
        assert sing.shape == (2, 3)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_per_element_singularity(self, rng):
        good = rng.standard_normal((8, 8))
        bad = np.ones((8, 8))
        a = jnp.asarray(np.stack([good, bad, good]))
        inv, sing = batched_jordan_invert(a, block_size=4)
        assert list(np.asarray(sing)) == [False, True, False]
        np.testing.assert_allclose(
            np.asarray(inv[0]), np.linalg.inv(good), rtol=1e-8, atol=1e-8
        )

    @pytest.mark.slow
    def test_smalln_engine_bitmatches_vmapped(self, rng):
        # The dedicated small-n batch engine (VERDICT r4 #5) must be
        # bit-identical to vmap of the unrolled in-place engine — same
        # pivot rule, same summation order, element for element.
        import jax

        from tpu_jordan.ops import block_jordan_invert_inplace
        from tpu_jordan.ops.batched import _batched_smalln

        a = jnp.asarray(rng.standard_normal((40, 48, 48)), jnp.float64)
        inv_b, sing_b = _batched_smalln(a, 16, None,
                                        jax.lax.Precision.HIGHEST, 0,
                                        False)
        inv_v, sing_v = jax.vmap(
            lambda x: block_jordan_invert_inplace(x, block_size=16))(a)
        assert bool((sing_b == sing_v).all())
        assert bool((inv_b == inv_v).all()), "small-n batch engine diverged"

    def test_smalln_engine_per_element_singularity_and_swaps(self, rng):
        # Pivoting fixtures per element: |i-j| (zero diagonal — swaps
        # required) mixed with a singular element and a random one.
        import jax

        from tpu_jordan.ops.batched import _batched_smalln

        i = np.arange(48)
        absd = np.abs(i[:, None] - i[None, :]).astype(float)
        good = rng.standard_normal((48, 48))
        a = np.stack([absd, np.ones((48, 48)), good] * 12)   # B=36
        inv, sing = _batched_smalln(jnp.asarray(a), 8, None,
                                    jax.lax.Precision.HIGHEST, 0, False)
        sing = np.asarray(sing)
        assert list(sing[:3]) == [False, True, False]
        assert (sing.reshape(-1, 3) == [False, True, False]).all()
        np.testing.assert_allclose(np.asarray(inv[0]), np.linalg.inv(absd),
                                   rtol=1e-8, atol=1e-8)

    def test_smalln_dispatch_and_ragged(self, rng):
        # Nr <= 4 and B >= 32 routes through the dedicated engine,
        # including ragged n (identity padding) and sub-fp32 storage.
        a = rng.standard_normal((32, 50, 50))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=16)
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(np.asarray(inv), np.linalg.inv(a),
                                   rtol=1e-7, atol=1e-7)
        b16 = batched_jordan_invert(
            jnp.asarray(a[:32], jnp.bfloat16), block_size=8)[0]
        assert b16.dtype == jnp.bfloat16

    def test_large_batch_routes_through_fori_engine(self, rng, monkeypatch):
        # Large B x many probe shapes is a measured-failing compile
        # region for the unrolled engine on TPU (PHASES.md "compile
        # lottery"); the dispatch must route big batches through the
        # fori engine (one probe shape), and results must agree.
        import tpu_jordan.ops.batched as batched_mod
        import tpu_jordan.ops.jordan_inplace as ji

        calls = []
        orig = ji.block_jordan_invert_inplace_fori

        def spy(x, **kw):
            calls.append(x.shape)
            return orig(x, **kw)

        monkeypatch.setattr(ji, "block_jordan_invert_inplace_fori", spy)
        # Nr = 48/8 = 6 > 4 and B*Nr = 132 >= 128 -> fori route.
        a = rng.standard_normal((22, 48, 48))
        inv, sing = batched_mod.batched_jordan_invert(
            jnp.asarray(a), block_size=8)
        assert calls, "fori engine was not selected for the large batch"
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-6, atol=1e-6
        )

    def test_inplace_engine_selected_and_agrees(self, rng, monkeypatch):
        # Nr <= MAX_UNROLL_NR must route through the vmapped in-place
        # engine (the 2x-flops win applies to batches too); its results
        # must match the augmented engine.
        import tpu_jordan.driver as driver_mod
        from tpu_jordan.ops.jordan_inplace import block_jordan_invert_inplace

        calls = []
        orig = driver_mod.single_device_invert

        def spy(n, m):
            engine = orig(n, m)
            calls.append(engine is block_jordan_invert_inplace)
            return engine

        monkeypatch.setattr(driver_mod, "single_device_invert", spy)
        a = rng.standard_normal((4, 32, 32))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert calls and all(calls), "in-place engine was not selected"
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_augmented_fallback_large_Nr(self, rng):
        # Nr > MAX_UNROLL_NR: the fori_loop engine takes over (no
        # unrolled-trace blowup for many tiny blocks).
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n, m = 2 * (MAX_UNROLL_NR + 2), 2
        assert -(-n // m) > MAX_UNROLL_NR
        a = rng.standard_normal((2, n, n))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=m)
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-6, atol=1e-6
        )
