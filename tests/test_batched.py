"""Batched inversion (ops/batched.py) — the vmap capability beyond the
reference (BASELINE.md north star: batched Jordan solves)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_jordan.ops import batched_jordan_invert


class TestBatchedInvert:
    def test_stack_matches_linalg(self, rng):
        a = rng.standard_normal((6, 24, 24))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert inv.shape == (6, 24, 24)
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_nested_batch_dims(self, rng):
        a = rng.standard_normal((2, 3, 16, 16))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert inv.shape == (2, 3, 16, 16)
        assert sing.shape == (2, 3)
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    @pytest.mark.smoke      # the batched-family parity + flag case
    def test_per_element_singularity(self, rng):
        good = rng.standard_normal((8, 8))
        bad = np.ones((8, 8))
        a = jnp.asarray(np.stack([good, bad, good]))
        inv, sing = batched_jordan_invert(a, block_size=4)
        assert list(np.asarray(sing)) == [False, True, False]
        np.testing.assert_allclose(
            np.asarray(inv[0]), np.linalg.inv(good), rtol=1e-8, atol=1e-8
        )

    @pytest.mark.slow
    def test_smalln_engine_bitmatches_vmapped(self, rng):
        # The dedicated small-n batch engine (VERDICT r4 #5) must be
        # bit-identical to vmap of the unrolled in-place engine — same
        # pivot rule, same summation order, element for element.
        import jax

        from tpu_jordan.ops import block_jordan_invert_inplace
        from tpu_jordan.ops.batched import _batched_smalln

        a = jnp.asarray(rng.standard_normal((40, 48, 48)), jnp.float64)
        inv_b, sing_b = _batched_smalln(a, 16, None,
                                        jax.lax.Precision.HIGHEST, 0,
                                        False)
        inv_v, sing_v = jax.vmap(
            lambda x: block_jordan_invert_inplace(x, block_size=16))(a)
        assert bool((sing_b == sing_v).all())
        assert bool((inv_b == inv_v).all()), "small-n batch engine diverged"

    @pytest.mark.slow  # tier-1 budget: the batched smoke parity case stays
    def test_smalln_engine_per_element_singularity_and_swaps(self, rng):
        # Pivoting fixtures per element: |i-j| (zero diagonal — swaps
        # required) mixed with a singular element and a random one.
        import jax

        from tpu_jordan.ops.batched import _batched_smalln

        i = np.arange(48)
        absd = np.abs(i[:, None] - i[None, :]).astype(float)
        good = rng.standard_normal((48, 48))
        a = np.stack([absd, np.ones((48, 48)), good] * 12)   # B=36
        inv, sing = _batched_smalln(jnp.asarray(a), 8, None,
                                    jax.lax.Precision.HIGHEST, 0, False)
        sing = np.asarray(sing)
        assert list(sing[:3]) == [False, True, False]
        assert (sing.reshape(-1, 3) == [False, True, False]).all()
        np.testing.assert_allclose(np.asarray(inv[0]), np.linalg.inv(absd),
                                   rtol=1e-8, atol=1e-8)

    def test_smalln_dispatch_and_ragged(self, rng):
        # Nr <= 4 and B >= 32 routes through the dedicated engine,
        # including ragged n (identity padding) and sub-fp32 storage.
        a = rng.standard_normal((32, 50, 50))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=16)
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(np.asarray(inv), np.linalg.inv(a),
                                   rtol=1e-7, atol=1e-7)
        b16 = batched_jordan_invert(
            jnp.asarray(a[:32], jnp.bfloat16), block_size=8)[0]
        assert b16.dtype == jnp.bfloat16

    def test_large_batch_routes_through_fori_engine(self, rng, monkeypatch):
        # Large B x many probe shapes is a measured-failing compile
        # region for the unrolled engine on TPU (PHASES.md "compile
        # lottery"); the dispatch must route big batches through the
        # fori engine (one probe shape), and results must agree.
        import tpu_jordan.ops.batched as batched_mod
        import tpu_jordan.ops.jordan_inplace as ji

        calls = []
        orig = ji.block_jordan_invert_inplace_fori

        def spy(x, **kw):
            calls.append(x.shape)
            return orig(x, **kw)

        monkeypatch.setattr(ji, "block_jordan_invert_inplace_fori", spy)
        # Nr = 48/8 = 6 > 4 and B*Nr = 132 >= 128 -> fori route.
        a = rng.standard_normal((22, 48, 48))
        inv, sing = batched_mod.batched_jordan_invert(
            jnp.asarray(a), block_size=8)
        assert calls, "fori engine was not selected for the large batch"
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-6, atol=1e-6
        )

    def test_inplace_engine_selected_and_agrees(self, rng, monkeypatch):
        # Nr <= MAX_UNROLL_NR must route through the vmapped in-place
        # engine (the 2x-flops win applies to batches too); its results
        # must match the augmented engine.
        import tpu_jordan.driver as driver_mod
        from tpu_jordan.ops.jordan_inplace import block_jordan_invert_inplace

        calls = []
        orig = driver_mod.single_device_invert

        def spy(n, m):
            engine = orig(n, m)
            calls.append(engine is block_jordan_invert_inplace)
            return engine

        monkeypatch.setattr(driver_mod, "single_device_invert", spy)
        a = rng.standard_normal((4, 32, 32))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=8)
        assert calls and all(calls), "in-place engine was not selected"
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-8, atol=1e-8
        )

    def test_mixed_singular_batch_does_not_poison_healthy_gates(self, rng):
        # ISSUE 3 satellite: the service depends on a mixed
        # singular/nonsingular batch reporting per-element flags while
        # the HEALTHY elements' accuracy metrics stay gate-clean — a
        # batch-wide abort (solve_batch's SingularMatrixError) would
        # poison every rider of the batch.
        from tpu_jordan.driver import batch_metrics

        good = [rng.standard_normal((48, 48)) for _ in range(3)]
        a = jnp.asarray(np.stack(
            [good[0], np.ones((48, 48)), good[1], np.zeros((48, 48)),
             good[2]]))
        inv, sing = batched_jordan_invert(a, block_size=16)
        assert list(np.asarray(sing)) == [False, True, False, True, False]
        met = batch_metrics(a, inv)
        rel = np.asarray(met["rel_residual"])
        kap = np.asarray(met["kappa"])
        healthy = ~np.asarray(sing)
        # Healthy elements pass the standard residual gate; their κ∞ is
        # finite and positive — nothing about the singular neighbors
        # leaked into their rows.
        assert (rel[healthy] < 1e-5).all(), rel
        assert (kap[healthy] > 0).all() and np.isfinite(kap[healthy]).all()
        for i, g in zip((0, 2, 4), good):
            np.testing.assert_allclose(np.asarray(inv[i]), np.linalg.inv(g),
                                       rtol=1e-8, atol=1e-8)

    def test_batch_of_one_bitmatches_unbatched_engine(self, rng):
        # ISSUE 3 satellite (batch_cap=1 contract): a single-element
        # batch through the batched machinery is EXACTLY the unbatched
        # engine — bit for bit, flags included.
        from tpu_jordan.ops import block_jordan_invert_inplace

        a = rng.standard_normal((64, 64))
        inv_b, sing_b = batched_jordan_invert(jnp.asarray(a)[None],
                                              block_size=16)
        inv_s, sing_s = block_jordan_invert_inplace(jnp.asarray(a),
                                                    block_size=16)
        assert bool(sing_b[0]) == bool(sing_s) is False
        assert bool(jnp.all(inv_b[0] == inv_s)), \
            "B=1 batched result diverged from the unbatched engine"

    def test_batch_metrics_masks_identity_padding(self, rng):
        # The row mask is load-bearing: identity-pad rows abs-sum to
        # exactly 1 and would otherwise cap a small true norm (the
        # serve executors' bucketed stacks hit this on every request).
        from tpu_jordan.driver import batch_metrics
        from tpu_jordan.ops import pad_with_identity

        a = 0.01 * rng.standard_normal((24, 24))
        pad = jnp.stack([pad_with_identity(jnp.asarray(a), 32)])
        inv, sing = batched_jordan_invert(pad, block_size=8)
        assert not bool(sing[0])
        masked = batch_metrics(pad, inv, n_real=jnp.asarray([24]))
        unmasked = batch_metrics(pad, inv)
        want_norm = float(np.max(np.sum(np.abs(a), axis=-1)))
        assert float(masked["norm_a"][0]) == pytest.approx(want_norm)
        assert float(unmasked["norm_a"][0]) == pytest.approx(1.0)
        # A fully-masked filler slot (n_real=0) reports zeros, not NaN.
        filler = batch_metrics(pad, inv, n_real=jnp.asarray([0]))
        assert float(filler["rel_residual"][0]) == 0.0
        assert float(filler["kappa"][0]) == 0.0

    def test_augmented_fallback_large_Nr(self, rng):
        # Nr > MAX_UNROLL_NR: the fori_loop engine takes over (no
        # unrolled-trace blowup for many tiny blocks).
        from tpu_jordan.parallel.sharded_inplace import MAX_UNROLL_NR

        n, m = 2 * (MAX_UNROLL_NR + 2), 2
        assert -(-n // m) > MAX_UNROLL_NR
        a = rng.standard_normal((2, n, n))
        inv, sing = batched_jordan_invert(jnp.asarray(a), block_size=m)
        assert not np.asarray(sing).any()
        np.testing.assert_allclose(
            np.asarray(inv), np.linalg.inv(a), rtol=1e-6, atol=1e-6
        )
