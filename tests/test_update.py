"""ISSUE 12 — resident-inverse handles and Sherman–Morrison–Woodbury
rank-k updates: the SMW identity vs a from-scratch inverse, exact
zero-pad bucketing, typed singularity (det(A+UVᵀ) = det(A)·det(S) —
the rank-deficient Gram edge included), the drift-budget accumulation
ladder with its exact crossing point, the serve update lane's warm
zero-compile/zero-measurement pins (plain run AND across a fleet
rolling restart), the compiled-executable FLOP pin (update strictly
below fresh invert at k ≤ n/8), replica-kill durability of the shared
handle store, and the ``check_update.py`` both-ways gate."""

import importlib.util
import pathlib

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_jordan.linalg.update import (DRIFT_BUDGET_FACTOR, drift_budget,
                                      drift_exceeded, smw_update,
                                      smw_update_with_metrics,
                                      solve_update)


def _factors(rng, n, k, dtype=np.float32, scale=None):
    s = (1.0 / np.sqrt(float(n) * k)) if scale is None else scale
    return (rng.standard_normal((n, k)).astype(dtype) * s,
            rng.standard_normal((n, k)).astype(dtype) * s)


class TestSMWIdentity:
    def test_matches_fresh_inverse(self, rng):
        n, k = 40, 3
        a = rng.standard_normal((n, n)).astype(np.float32)
        inv = np.linalg.inv(a).astype(np.float32)
        u, v = _factors(rng, n, k)
        got, sing = smw_update(jnp.asarray(inv), jnp.asarray(u),
                               jnp.asarray(v))
        assert not bool(sing)
        want = np.linalg.inv(a + u @ v.T)
        assert np.abs(np.asarray(got) - want).max() < 1e-4

    def test_zero_pad_columns_exact(self, rng):
        """The k-bucket contract: zero-padded U/V columns change NO
        bits — pad columns contribute nothing to U·Vᵀ and the
        capacitance pad block is the identity."""
        n, k, kb = 24, 3, 8
        a = rng.standard_normal((n, n)).astype(np.float32)
        inv = np.linalg.inv(a).astype(np.float32)
        u, v = _factors(rng, n, k)
        up = np.zeros((n, kb), np.float32)
        vp = np.zeros((n, kb), np.float32)
        up[:, :k], vp[:, :k] = u, v
        bare, s1 = smw_update(jnp.asarray(inv), jnp.asarray(u),
                              jnp.asarray(v))
        padded, s2 = smw_update(jnp.asarray(inv), jnp.asarray(up),
                                jnp.asarray(vp))
        assert not bool(s1) and not bool(s2)
        assert (np.asarray(bare) == np.asarray(padded)).all()

    def test_with_metrics_verifies_against_mutated_matrix(self, rng):
        n, k = 32, 2
        a = rng.standard_normal((n, n)).astype(np.float32)
        inv = np.linalg.inv(a).astype(np.float32)
        u, v = _factors(rng, n, k)
        a_new, inv_new, sing, kappa, rel = smw_update_with_metrics(
            jnp.asarray(a), jnp.asarray(inv), jnp.asarray(u),
            jnp.asarray(v))
        assert not bool(sing)
        assert np.allclose(np.asarray(a_new), a + u @ v.T, atol=1e-6)
        # rel is ‖A_new·X_new − I‖∞ / ‖A_new‖∞ — the invert convention.
        r = np.abs(np.asarray(a_new) @ np.asarray(inv_new)
                   - np.eye(n)).sum(axis=-1).max()
        na = np.abs(np.asarray(a_new)).sum(axis=-1).max()
        assert float(rel) == pytest.approx(r / na, rel=1e-3)
        assert float(kappa) > 0

    def test_sub_fp32_storage_rounds_once(self, rng):
        n, k = 16, 2
        a = rng.standard_normal((n, n)).astype(np.float32)
        inv = np.linalg.inv(a)
        u, v = _factors(rng, n, k)
        got, sing = smw_update(jnp.asarray(inv, jnp.bfloat16),
                               jnp.asarray(u, jnp.bfloat16),
                               jnp.asarray(v, jnp.bfloat16))
        assert got.dtype == jnp.bfloat16
        assert not bool(sing)


class TestTypedSingularity:
    def test_rank_destroying_update_flags_capacitance(self, rng):
        """u = −A·e₀, v = e₀ zeroes column 0: det(S) = det(A+uvᵀ)/det(A)
        = 0 exactly — the capacitance solve must flag it, never emit
        garbage.  Exact arithmetic here: inv is the EXACT float64
        inverse so 1 + e₀ᵀA⁻¹u cancels to ~0 below the eps threshold."""
        n = 12
        a = rng.standard_normal((n, n)).astype(np.float64)
        inv = np.linalg.inv(a)
        u = -a[:, :1]
        v = np.zeros((n, 1))
        v[0, 0] = 1.0
        _, sing = smw_update(jnp.asarray(inv), jnp.asarray(u),
                             jnp.asarray(v))
        # Typed somewhere on the ladder: either the capacitance flags
        # it here, or (fp rounding slipping past eps) the serve gate +
        # re_invert rung types it — TestServeLane covers that end.
        from tpu_jordan.driver import SingularMatrixError

        if not bool(sing):
            with pytest.raises(SingularMatrixError):
                solve_update(a, inv, u, v,
                             policy=None, check=True)
        else:
            with pytest.raises(SingularMatrixError):
                solve_update(a, inv, u, v, check=True)

    def test_lstsq_rank_deficient_gram_edge_typed(self, rng):
        """The ISSUE 12 satellite edge: a resident GRAM inverse (the
        lstsq normal-equations shape) updated by a mutation that
        destroys A's column rank — (A'ᵀA') is singular and the update
        path must type it, never return a garbage pseudo-inverse."""
        rows, n = 20, 6
        a = rng.standard_normal((rows, n)).astype(np.float64)
        gram = a.T @ a
        gram_inv = np.linalg.inv(gram)
        # Make column 1 a copy of column 0: rank deficiency.  The Gram
        # mutation G' = A'ᵀA' − AᵀA is rank-2 symmetric: G' = U·Vᵀ with
        # U = [d, s], V = [s, d]/shared — build it exactly.
        a2 = a.copy()
        a2[:, 1] = a2[:, 0]
        gram2 = a2.T @ a2
        # Factor the symmetric difference exactly via its eigendecomp.
        diff = gram2 - gram
        w, q = np.linalg.eigh(diff)
        keep = np.abs(w) > 1e-12
        u = q[:, keep] * w[keep]
        v = q[:, keep]
        assert u.shape[1] <= 4
        res = solve_update(gram, gram_inv, u, v, check=False)
        if not res.singular:
            # The capacitance rounded past eps: the GATE must still
            # refuse the garbage inverse (rel residual of a singular
            # system cannot pass eps·n·κ with finite κ).
            assert not np.isfinite(res.rel_residual) or \
                res.rel_residual > 1e-3
        else:
            assert res.inverse is None


class TestDriftBudget:
    def test_documented_budget_factor(self):
        assert drift_budget(0.25) == DRIFT_BUDGET_FACTOR * 0.25

    def test_exact_crossing_point(self):
        """m small updates whose SUMMED drift crosses the budget
        exactly at the documented threshold: m·d <= F·thr passes,
        the first update past it fires."""
        thr = 0.125                  # binary-exact: the crossing is
        budget = drift_budget(thr)   # judged at the boundary, so the
        d = budget / 8.0             # fixture must sum without rounding
        drift = 0.0
        fired_at = None
        for i in range(1, 12):
            drift += d
            if drift_exceeded(drift, budget):
                fired_at = i
                break
        # 8·d == budget exactly (<= passes); the 9th crosses.
        assert fired_at == 9

    def test_nan_hostile(self):
        assert drift_exceeded(float("nan"), 1.0)
        assert drift_exceeded(1.0, float("nan"))
        assert not drift_exceeded(0.0, 0.0)

    def test_factor_override(self):
        assert drift_budget(1.0, factor=0.0) == 0.0
        assert drift_exceeded(1e-12, drift_budget(1.0, factor=0.0))


class TestSolveUpdateAPI:
    def test_result_surface_and_drift_threading(self, rng):
        n, k = 24, 2
        a = rng.standard_normal((n, n)).astype(np.float32)
        inv = np.linalg.inv(a).astype(np.float32)
        u, v = _factors(rng, n, k)
        r1 = solve_update(a, inv, u, v)
        assert r1.workload == "update" and r1.engine == "smw_update"
        assert r1.n == n and r1.k == k
        assert r1.drift >= r1.rel_residual >= 0
        assert r1.gflops >= 0
        # Drift accumulates across chained updates.
        u2, v2 = _factors(rng, n, k)
        r2 = solve_update(np.asarray(r1.a_new), np.asarray(r1.inverse),
                          u2, v2, drift=r1.drift)
        assert r2.drift > r1.drift

    def test_policy_gate_and_re_invert_rung(self, rng):
        """A policy-attached update whose accumulated drift is doctored
        past the budget fires the re_invert rung (a fresh elimination
        of the mutated matrix), passes, and resets drift to 0."""
        from tpu_jordan.resilience import ResiliencePolicy

        n, k = 24, 2
        a = rng.standard_normal((n, n)).astype(np.float32)
        inv = np.linalg.inv(a).astype(np.float32)
        u, v = _factors(rng, n, k)
        res = solve_update(a, inv, u, v, policy=ResiliencePolicy(),
                           drift=1e9)
        assert res.recovery and res.recovery[0]["rung"] == "re_invert"
        assert res.recovery[0]["cause"] == "drift_budget"
        assert res.recovery[0]["passed"]
        assert res.drift == 0.0

    def test_shape_validation_typed(self, rng):
        from tpu_jordan.driver import UsageError

        n = 8
        a = np.eye(n, dtype=np.float32)
        with pytest.raises(UsageError, match="matching"):
            solve_update(a, a, np.zeros((n, 2), np.float32),
                         np.zeros((n, 3), np.float32))
        with pytest.raises(UsageError, match="trace"):
            solve_update(a, a, np.zeros((n, 1), np.float32),
                         np.zeros((n, 1), np.float32), numerics="trace")


class TestHandleStore:
    def test_unknown_handle_typed(self):
        from tpu_jordan.serve import HandleStore, UnknownHandleError

        store = HandleStore()
        with pytest.raises(UnknownHandleError):
            store.get("nope")
        assert not store.evict("nope")

    def test_commit_and_eviction(self):
        from tpu_jordan.serve.handles import HandleState, HandleStore

        store = HandleStore()
        st = HandleState(handle_id="x", n=4, bucket_n=64,
                         dtype="float32", a=np.eye(4), inverse=np.eye(4))
        ref = store.create(st)
        assert ref.handle_id == "x" and len(store) == 1
        with store.txn("x") as live:
            store.commit(live, a=2 * np.eye(4), inverse=0.5 * np.eye(4),
                         kappa=1.0, rel_residual=1e-6, drift=1e-6)
        got = store.get("x")
        assert got.version == 1 and got.updates_applied == 1
        assert store.snapshot()["x"]["version"] == 1
        assert store.evict("x") and len(store) == 0


class TestHandleStoreRaces:
    def test_evict_waits_out_in_flight_txn(self):
        """Review hardening: an evict racing an in-flight update must
        WAIT (the handle's own lock), so a committed update is never
        orphaned into a state the store no longer serves."""
        import threading
        import time

        from tpu_jordan.serve.handles import (HandleState, HandleStore,
                                              UnknownHandleError)

        store = HandleStore()
        store.create(HandleState(handle_id="x", n=4, bucket_n=64,
                                 dtype="float32", a=np.eye(4),
                                 inverse=np.eye(4)))
        entered = threading.Event()
        release = threading.Event()
        versions = []

        def updater():
            with store.txn("x") as live:
                entered.set()
                release.wait(10)
                store.commit(live, a=np.eye(4), inverse=np.eye(4),
                             kappa=1.0, rel_residual=0.0, drift=0.0)
                versions.append(live.version)

        t = threading.Thread(target=updater)
        t.start()
        assert entered.wait(10)
        evictor = threading.Thread(target=lambda: store.evict("x"))
        evictor.start()
        time.sleep(0.05)
        assert evictor.is_alive()     # blocked on the txn, not racing it
        release.set()
        t.join(10)
        evictor.join(10)
        assert versions == [1]        # the commit landed first ...
        with pytest.raises(UnknownHandleError):
            store.get("x")            # ... THEN the evict removed it

    def test_txn_on_replaced_handle_lands_on_successor(self):
        """create() over an existing id REPLACES the state; a txn that
        raced the swap retries onto the successor — never the orphan."""
        from tpu_jordan.serve.handles import HandleState, HandleStore

        store = HandleStore()
        store.create(HandleState(handle_id="x", n=4, bucket_n=64,
                                 dtype="float32", a=np.eye(4),
                                 inverse=np.eye(4)))
        fresh = HandleState(handle_id="x", n=4, bucket_n=64,
                            dtype="float32", a=2 * np.eye(4),
                            inverse=0.5 * np.eye(4))
        store.create(fresh)           # the re-create
        with store.txn("x") as live:
            assert live is fresh
            assert live.version == 0  # version restarted with the swap


class TestServeLane:
    @pytest.mark.smoke       # the resident-handle round trip (smoke)
    def test_resident_round_trip_submit_update_verified(self, rng):
        """submit → update → verified result: the smoke-tier pin for
        the whole resident path (create, O(n²k) refresh, in-launch
        verification against the mutated matrix, write-through)."""
        from tpu_jordan.serve import JordanService

        n, k = 48, 3
        a = rng.standard_normal((n, n)).astype(np.float32)
        u, v = _factors(rng, n, k)
        with JordanService(batch_cap=2, max_wait_ms=0.5) as svc:
            svc.warmup(update_shapes=[(n, k)])
            warm = svc.stats()["totals"]["compiles"]
            ref = svc.invert(a, resident=True, timeout=120)
            assert ref.bucket_n == 64 and ref.result.rel_residual < 1e-4
            res = svc.update(ref, u, v, timeout=120)
            stats = svc.stats()
        assert res.workload == "update"
        assert res.update_outcome == "refreshed"
        assert res.handle_version == 1
        assert res.rel_residual < 1e-3
        want = np.linalg.inv(a + u @ v.T)
        assert np.abs(np.asarray(res.inverse) - want).max() < 1e-3
        # Warm pins: zero compiles on the whole request path, zero
        # plan-cache measurements, and the update traffic accounted.
        assert stats["totals"]["compiles"] == warm
        assert stats["measurements"] == 0
        assert stats["workloads"]["update"]["requests"] == 1
        assert stats["handles"][ref.handle_id]["version"] == 1

    def test_flops_pin_update_below_fresh_invert(self):
        """The acceptance FLOP pin: the update executable's own XLA
        cost_analysis FLOPs sit STRICTLY below the same-n fresh-invert
        executable's at k ≤ n/8 — even though the update deliberately
        carries the full O(n³) verification matmul."""
        from tpu_jordan.serve import JordanService, k_bucket_for

        n, k = 128, 16          # k = n/8, the documented boundary
        with JordanService(batch_cap=1, max_wait_ms=0.5) as svc:
            svc.warmup(update_shapes=[(n, k)])
            ex_upd = svc.executors.get(n, 1, svc._batcher.block_size,
                                       workload="update",
                                       rhs=k_bucket_for(k))
            ex_inv = svc.executors.get(n, 1, svc._batcher.block_size)
        if not (ex_upd.cost.available and ex_upd.cost.flops
                and ex_inv.cost.available and ex_inv.cost.flops):
            pytest.skip("backend exposes no cost_analysis")
        assert ex_upd.cost.flops < ex_inv.cost.flops, (
            f"update executable {ex_upd.cost.flops:.3g} FLOPs not "
            f"below fresh invert {ex_inv.cost.flops:.3g}")

    def test_singular_update_gated_handle_untouched(self, rng):
        from tpu_jordan.serve import JordanService

        n, k = 32, 2
        a = rng.standard_normal((n, n)).astype(np.float32)
        with JordanService(batch_cap=1, max_wait_ms=0.5) as svc:
            ref = svc.invert(a, resident=True, timeout=120)
            st = svc.handles.get(ref.handle_id)
            u = np.zeros((n, k), np.float32)
            v = np.zeros((n, k), np.float32)
            u[:, 0] = -np.asarray(st.a[:n, 0])
            v[0, 0] = 1.0
            res = svc.submit_update(ref, u, v).result(120)
            assert res.singular and res.update_outcome == "gated"
            assert svc.handles.get(ref.handle_id).version == 0
            # The sync surface raises typed; state still untouched.
            from tpu_jordan.driver import SingularMatrixError

            with pytest.raises(SingularMatrixError):
                svc.update(ref, u, v, timeout=120)
            # A later healthy update still lands.
            u2, v2 = _factors(rng, n, k)
            ok = svc.update(ref, u2, v2, timeout=120)
            assert ok.update_outcome == "refreshed"
            assert ok.handle_version == 1

    def test_forced_drift_budget_fires_re_invert_rung(self, rng):
        from tpu_jordan.obs.metrics import REGISTRY
        from tpu_jordan.serve import JordanService

        n, k = 32, 2
        a = rng.standard_normal((n, n)).astype(np.float32)
        u, v = _factors(rng, n, k)
        rungs = REGISTRY.counter("tpu_jordan_recovery_rungs_total")
        before = rungs.total()
        with JordanService(batch_cap=1, max_wait_ms=0.5,
                           update_drift_budget_factor=0.0) as svc:
            svc.warmup(update_shapes=[(n, k)])
            warm = svc.stats()["totals"]["compiles"]
            ref = svc.invert(a, resident=True, timeout=120)
            res = svc.update(ref, u, v, timeout=120)
            compiles = svc.stats()["totals"]["compiles"]
        assert res.update_outcome == "re_inverted"
        assert res.drift == 0.0
        assert rungs.total() == before + 1
        # The rung rode the WARM invert lane: still zero compiles.
        assert compiles == warm
        want = np.linalg.inv(a + u @ v.T)
        assert np.abs(np.asarray(res.inverse) - want).max() < 1e-3

    def test_summary_spike_causally_precedes_update_rung(self, rng):
        """Review hardening (the ISSUE 10 causality discipline on the
        update lane): a drift-forced re_invert rung under
        numerics='summary' is preceded — by seq — by a numerics_spike
        (signal='drift'): the budget exceedance records its own
        breadcrumb, since every individual residual passed the gate."""
        from tpu_jordan.obs.recorder import RECORDER
        from tpu_jordan.serve import JordanService

        n, k = 32, 2
        a = rng.standard_normal((n, n)).astype(np.float32)
        u, v = _factors(rng, n, k)
        mark = RECORDER.total
        with JordanService(batch_cap=1, max_wait_ms=0.5,
                           numerics="summary",
                           update_drift_budget_factor=0.0) as svc:
            svc.warmup(update_shapes=[(n, k)])
            ref = svc.invert(a, resident=True, timeout=120)
            res = svc.update(ref, u, v, timeout=120)
        assert res.update_outcome == "re_inverted"
        events = RECORDER.since(mark)
        spikes = [e["seq"] for e in events
                  if e["kind"] == "numerics_spike"]
        rungs = [e["seq"] for e in events
                 if e["kind"] == "recovery_rung"]
        assert spikes and rungs and min(spikes) < min(rungs)
        assert any(e.get("signal") == "drift" for e in events
                   if e["kind"] == "numerics_spike")

    def test_deadline_exceeded_leaves_handle_untouched(self, rng):
        """A typed update failure NEVER leaves a half-trusted
        mutation: a deadline-expired update fails typed with the
        committed state (and version) untouched."""
        from tpu_jordan.resilience.policy import DeadlineExceededError
        from tpu_jordan.serve import JordanService

        n, k = 24, 2
        a = rng.standard_normal((n, n)).astype(np.float32)
        u, v = _factors(rng, n, k)
        with JordanService(batch_cap=1, max_wait_ms=0.5) as svc:
            ref = svc.invert(a, resident=True, timeout=120)
            fut = svc.submit_update(ref, u, v, deadline_ms=0.0)
            with pytest.raises(DeadlineExceededError):
                fut.result(60)
            assert svc.handles.get(ref.handle_id).version == 0

    def test_update_against_unknown_handle_typed(self):
        from tpu_jordan.serve import (HandleRef, JordanService,
                                      UnknownHandleError)

        with JordanService(batch_cap=1, max_wait_ms=0.5) as svc:
            ghost = HandleRef("ghost", 16, 64, "float32")
            fut = svc.submit_update(ghost, np.zeros((16, 1), np.float32),
                                    np.zeros((16, 1), np.float32))
            with pytest.raises(UnknownHandleError):
                fut.result(60)

    def test_typed_failures_never_trip_the_lane_breaker(self, rng):
        """Review hardening: typed caller/numerics outcomes (an
        evicted/unknown handle) are THAT rider's answer, not
        lane-health evidence — K of them in a row must NOT open the
        update lane's breaker or shed healthy handles' traffic."""
        from tpu_jordan.serve import (HandleRef, JordanService,
                                      UnknownHandleError)

        n, k = 32, 2
        a = rng.standard_normal((n, n)).astype(np.float32)
        u, v = _factors(rng, n, k)
        with JordanService(batch_cap=1, max_wait_ms=0.5) as svc:
            svc.warmup(update_shapes=[(n, k)])
            ref = svc.invert(a, resident=True, timeout=120)
            ghost = HandleRef("ghost", n, ref.bucket_n, "float32")
            for _ in range(5):        # > the breaker's K=3
                with pytest.raises(UnknownHandleError):
                    svc.submit_update(ghost, u, v).result(60)
            # The lane still serves healthy handles — no CircuitOpen.
            ok = svc.update(ref, u, v, timeout=120)
            assert ok.update_outcome == "refreshed"
            states = svc.stats()["breakers"]
        assert all(s != "open" for s in states.values()), states


class TestFleetDurability:
    @pytest.mark.slow  # tier-1 budget: test_rolling_restart_serves_updates_warm stays
    def test_kill_mid_update_stream_bitmatches_fault_free(self, rng):
        """The ISSUE 12 chaos pin at test scale: a seeded replica_kill
        mid-update-stream loses nothing — every per-update outcome AND
        the final resident inverse bit-match the fault-free replay
        (the shared HandleStore is the durability boundary), with zero
        compiles after warmup across the kill + warm replacement."""
        from tpu_jordan.fleet import JordanFleet
        from tpu_jordan.obs.metrics import REGISTRY
        from tpu_jordan.resilience import FaultPlan, activate

        n, k = 48, 4
        a = rng.standard_normal((n, n)).astype(np.float32)
        stream = [_factors(rng, n, k) for _ in range(5)]

        def run(plan):
            outs = []
            with JordanFleet(replicas=2, batch_cap=2, max_wait_ms=0.5,
                             stable_after_s=0.2,
                             liveness_deadline_s=5.0) as flt:
                flt.warmup([n], update_shapes=[(n, k)])
                warm = REGISTRY.counter(
                    "tpu_jordan_compiles_total").total()
                if plan is not None:
                    cm = activate(plan)
                    cm.__enter__()
                try:
                    ref = flt.invert(a, resident=True, handle_id="t",
                                     timeout=120)
                    for u, v in stream:
                        r = flt.update(ref, u, v, timeout=120)
                        outs.append((r.update_outcome, r.handle_version,
                                     np.asarray(r.inverse).tobytes()))
                finally:
                    if plan is not None:
                        cm.__exit__(None, None, None)
                final = np.asarray(
                    flt.handles.get("t").inverse).tobytes()
                compiles = REGISTRY.counter(
                    "tpu_jordan_compiles_total").total() - warm
            return outs, final, compiles

        base, base_final, c0 = run(None)
        plan = FaultPlan.seeded(0, points={"replica_kill": (1, 4)})
        chaos, chaos_final, c1 = run(plan)
        assert plan.injected_total >= 1
        assert chaos == base
        assert chaos_final == base_final
        assert c0 == 0 and c1 == 0
        assert [o[1] for o in base] == [1, 2, 3, 4, 5]

    def test_rolling_restart_serves_updates_warm(self, rng):
        """A supervisor-replaced replica serves the update lane with
        ZERO compiles (shared executor store + shared handle store:
        nothing replica-local to rebuild) — the warm-path pin across a
        rolling restart."""
        from tpu_jordan.fleet import JordanFleet
        from tpu_jordan.obs.metrics import REGISTRY

        n, k = 48, 4
        a = rng.standard_normal((n, n)).astype(np.float32)
        with JordanFleet(replicas=2, batch_cap=2, max_wait_ms=0.5,
                         stable_after_s=0.2, liveness_deadline_s=5.0,
                         autostart_supervisor=False) as flt:
            flt.warmup([n], update_shapes=[(n, k)])
            ref = flt.invert(a, resident=True, handle_id="r",
                             timeout=120)
            u, v = _factors(rng, n, k)
            r1 = flt.update(ref, u, v, timeout=120)
            warm = REGISTRY.counter("tpu_jordan_compiles_total").total()
            # Kill EVERY slot, then let the supervisor install warm
            # replacements (the worst rolling-restart instant).
            for slot in flt.slot_table():
                slot.replica.kill(reason="test")
            flt.supervisor.check()
            assert len(flt.live_replicas()) >= 1
            u2, v2 = _factors(rng, n, k)
            r2 = flt.update(ref, u2, v2, timeout=120)
            compiles_after = REGISTRY.counter(
                "tpu_jordan_compiles_total").total()
        assert r1.handle_version == 1 and r2.handle_version == 2
        assert compiles_after == warm
        assert r2.update_outcome == "refreshed"


class TestUpdateDemoAndChecker:
    def test_demo_report_valid_and_doctored_stale_exits_2(self, tmp_path):
        """Both-ways gate (the repo's checker discipline): a real
        small-scale demo report validates clean, and doctored-stale
        variants — a bit mismatch, a failed gate, an unaccounted
        update — each exit 2."""
        import copy
        import json

        from tpu_jordan.serve.update_demo import update_demo

        _repo = pathlib.Path(__file__).resolve().parent.parent
        spec = importlib.util.spec_from_file_location(
            "check_update", _repo / "tools" / "check_update.py")
        check_update = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(check_update)

        report = update_demo(n=128, rank=8, updates=4, replicas=2,
                             kills=1, seed=0)
        errs, stale = check_update.check(report)
        assert errs == [] and stale == [], (errs, stale)
        assert report["latency"]["update_beats_reinvert"]
        assert report["chaos"]["final_inverse_bitmatch_replay"]

        def rc(rep, tmp_name):
            p = tmp_path / tmp_name
            p.write_text(json.dumps(rep))
            return check_update.main([str(p)])

        assert rc(report, "ok.json") == 0
        d1 = copy.deepcopy(report)
        d1["chaos"]["final_inverse_bitmatch_replay"] = False
        d1["silent_stale"] = True
        assert rc(d1, "bits.json") == 2
        d2 = copy.deepcopy(report)
        d2["verification"]["gate_passes"] = False
        assert rc(d2, "gate.json") == 2
        d3 = copy.deepcopy(report)
        d3["chaos"]["ledger"]["refreshed"] -= 1
        assert rc(d3, "ledger.json") == 2

    def test_cli_usage_errors_exit_1(self):
        from tpu_jordan.__main__ import main

        assert main(["96", "32", "--update-demo", "--workers", "8",
                     "--quiet"]) == 1
        assert main(["96", "32", "--update-demo", "--batch", "4",
                     "--quiet"]) == 1
        assert main(["96", "32", "--update-demo", "--replicas", "1",
                     "--quiet"]) == 1
        assert main(["96", "32", "--update-demo", "--rank", "64",
                     "--quiet"]) == 1          # rank > n/8
        assert main(["96", "32", "--update-demo", "--updates", "2",
                     "--quiet"]) == 1
        assert main(["96", "32", "--update-demo", "--slo-report",
                     "--quiet"]) == 1
        assert main(["96", "32", "--update-demo", "--batch-cap", "4",
                     "--quiet"]) == 1
        assert main(["96", "32", "--update-demo", "--plan-cache",
                     "/tmp/p.json", "--quiet"]) == 1
        assert main(["96", "32", "--update-demo", "--scaling-floor",
                     "2.5", "--quiet"]) == 1
        # --rank/--updates outside --update-demo: typed usage errors.
        assert main(["96", "32", "--rank", "8", "--quiet"]) == 1
        assert main(["96", "32", "--updates", "9", "--quiet"]) == 1


class TestRegistryAndKeys:
    def test_update_workload_resolves_smw_engine(self):
        from tpu_jordan.tuning.plan_cache import plan_key
        from tpu_jordan.tuning.registry import TunePoint, candidates

        pt = TunePoint.create(256, 64, "float32", workers=1,
                              backend="cpu", workload="update")
        cands = candidates(pt)
        assert [c.name for c in cands] == ["smw_update"]
        assert plan_key(pt).endswith("|wupdate")
        # Invert keys stay byte-identical (no workload segment).
        base = TunePoint.create(256, 64, "float32", workers=1,
                                backend="cpu")
        assert "|w" not in plan_key(base)

    def test_update_flop_convention(self):
        from tpu_jordan.obs.hwcost import baseline_workload_flops

        n, k = 100, 10
        assert baseline_workload_flops(n, "update", k=k) == \
            4.0 * n * n * k + 2.0 * n * k * k

    def test_tune_refuses_update_workload_typed(self):
        """Review hardening: measuring the update workload is a typed
        refusal (one engine, nothing to rank) — never a silently
        mis-measured solve kernel landing under the |wupdate| key."""
        from tpu_jordan.driver import UsageError
        from tpu_jordan.tuning.registry import TunePoint, get
        from tpu_jordan.tuning.tuner import measure_config

        pt = TunePoint.create(64, 32, "float32", workers=1,
                              backend="cpu", workload="update")
        with pytest.raises(UsageError, match="nothing to measure"):
            measure_config(pt, get("smw_update"), samples=1)

    def test_k_bucket_rounding(self):
        from tpu_jordan.serve import MIN_UPDATE_K, k_bucket_for

        assert k_bucket_for(1) == MIN_UPDATE_K
        assert k_bucket_for(8) == 8
        assert k_bucket_for(9) == 16
        assert k_bucket_for(32) == 32
        with pytest.raises(ValueError):
            k_bucket_for(0)
