// Native matrix-file parser.
//
// TPU-native counterpart of the reference's read_matrix scanning core
// (main.cpp:209-282: fscanf("%lf") over n*n whitespace-separated numbers).
// The reference interleaves parsing with MPI_Sends to the cyclic owners;
// here parsing is a host-side bulk operation (the "scatter" is a sharded
// device_put in Python), so the native piece is a single tight strtod loop
// over the whole file — ~20x the throughput of fscanf and ~5x numpy's
// text parsing for large matrices.
//
// C ABI only (loaded via ctypes, no pybind11 in this image).

#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

// Parse up to max_count whitespace-separated doubles from `path` into
// `out`.  Returns the number parsed, or -1 if the file cannot be opened
// (the reference's -1 "cannot open", main.cpp:231-237).  A short or
// malformed file simply yields a smaller count — the caller maps that to
// the reference's -2 "cannot read" (main.cpp:255, 277).
long tj_parse_matrix_text(const char *path, double *out, long max_count) {
  FILE *f = std::fopen(path, "rb");
  if (!f)
    return -1;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (size < 0) {
    std::fclose(f);
    return -1;
  }
  char *buf = (char *)std::malloc((size_t)size + 1);
  if (!buf) {
    std::fclose(f);
    return -1;
  }
  size_t got = std::fread(buf, 1, (size_t)size, f);
  std::fclose(f);
  buf[got] = '\0';

  long count = 0;
  const char *p = buf;
  char *end = nullptr;
  while (count < max_count) {
    double v = std::strtod(p, &end);
    if (end == p)
      break; // no progress: end of data or garbage token
    out[count++] = v;
    p = end;
  }
  std::free(buf);
  return count;
}

// Write a matrix in the reference's format (row-major, whitespace
// separated) so files round-trip through the reference binary.
long tj_write_matrix_text(const char *path, const double *data, long rows,
                          long cols) {
  FILE *f = std::fopen(path, "wb");
  if (!f)
    return -1;
  for (long i = 0; i < rows; i++) {
    for (long j = 0; j < cols; j++) {
      if (std::fprintf(f, "%.17g%c", data[i * cols + j],
                       j + 1 == cols ? '\n' : ' ') < 0) {
        std::fclose(f);
        return -2; // write failure (e.g. disk full)
      }
    }
  }
  if (std::fclose(f) != 0)
    return -2; // buffered data lost on close
  return rows * cols;
}

} // extern "C"
