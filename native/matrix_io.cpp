// Native matrix-file parser.
//
// TPU-native counterpart of the reference's read_matrix scanning core
// (main.cpp:209-282: fscanf("%lf") over n*n whitespace-separated numbers).
// The reference interleaves parsing with MPI_Sends to the cyclic owners;
// here parsing is a host-side bulk operation (the "scatter" is a sharded
// device_put in Python), so the native piece is a single tight strtod loop
// over the whole file — ~20x the throughput of fscanf and ~5x numpy's
// text parsing for large matrices.
//
// C ABI only (loaded via ctypes, no pybind11 in this image).

#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

// Parse up to max_count whitespace-separated doubles from `path` into
// `out`.  Returns the number parsed, or -1 if the file cannot be opened
// (the reference's -1 "cannot open", main.cpp:231-237).  A short or
// malformed file simply yields a smaller count — the caller maps that to
// the reference's -2 "cannot read" (main.cpp:255, 277).
long tj_parse_matrix_text(const char *path, double *out, long max_count) {
  FILE *f = std::fopen(path, "rb");
  if (!f)
    return -1;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (size < 0) {
    std::fclose(f);
    return -1;
  }
  char *buf = (char *)std::malloc((size_t)size + 1);
  if (!buf) {
    std::fclose(f);
    return -1;
  }
  size_t got = std::fread(buf, 1, (size_t)size, f);
  std::fclose(f);
  buf[got] = '\0';

  long count = 0;
  const char *p = buf;
  char *end = nullptr;
  while (count < max_count) {
    double v = std::strtod(p, &end);
    if (end == p)
      break; // no progress: end of data or garbage token
    out[count++] = v;
    p = end;
  }
  std::free(buf);
  return count;
}

// --- Streaming parser -------------------------------------------------
//
// Handle-based strip reader for the distributed file-scatter path: the
// reference's root rank reads ONE block-row buffer at a time and sends it
// to its owner (main.cpp:242-276), keeping host memory O(n*m).  These
// entry points give the Python side the same property: open once, pull
// `count` doubles per call, close.  Chunked fread + strtod; a number that
// straddles a chunk boundary is carried over to the next refill.

namespace {
constexpr size_t kChunk = 1 << 20; // 1 MiB read granularity
constexpr size_t kCarry = 64;      // headroom for a carried partial token

struct TjStream {
  FILE *f = nullptr;
  char *buf = nullptr;   // kChunk + kCarry + NUL
  size_t len = 0;        // valid bytes in buf
  size_t pos = 0;        // parse cursor
  bool eof = false;
};

// Ensure the unparsed tail is at the front of the buffer and the buffer
// is as full as the file allows.  Returns false once fully drained.
// The fread is clamped to the buffer's remaining capacity: callers keep
// the carried tail <= kCarry, but an oversized tail must degrade to a
// shorter read, never a heap overflow.
bool tj_refill(TjStream *s) {
  size_t tail = s->len - s->pos;
  if (tail > 0)
    std::memmove(s->buf, s->buf + s->pos, tail);
  s->len = tail;
  s->pos = 0;
  if (!s->eof) {
    size_t cap = kChunk + kCarry - s->len;
    size_t want = cap < kChunk ? cap : kChunk;
    size_t got = want ? std::fread(s->buf + s->len, 1, want, s->f) : 0;
    s->len += got;
    if (got < want)
      s->eof = true;
  }
  s->buf[s->len] = '\0';
  return s->len > 0;
}
} // namespace

void *tj_stream_open(const char *path) {
  FILE *f = std::fopen(path, "rb");
  if (!f)
    return nullptr;
  TjStream *s = new TjStream;
  s->f = f;
  // kCarry headroom for a carried-over partial token (longest printf
  // %.17g rendering is ~25 chars).
  s->buf = (char *)std::malloc(kChunk + kCarry + 1);
  if (!s->buf) {
    std::fclose(f);
    delete s;
    return nullptr;
  }
  s->buf[0] = '\0';
  return s;
}

// Parse up to `count` doubles into `out`; returns the number parsed
// (fewer only at end-of-data or on a malformed token).
long tj_stream_read(void *handle, double *out, long count) {
  TjStream *s = (TjStream *)handle;
  long parsed = 0;
  while (parsed < count) {
    char *end = nullptr;
    double v = std::strtod(s->buf + s->pos, &end);
    if (end == s->buf + s->pos) {
      // No progress: whitespace-only tail, partial token, or garbage.
      // Skip whitespace explicitly FIRST so the tail carried into
      // tj_refill is only ever a (possibly partial) token, never an
      // unbounded whitespace run — that run used to overflow the
      // kCarry headroom.
      while (s->pos < s->len &&
             std::isspace((unsigned char)s->buf[s->pos]))
        s->pos++;
      if (s->pos < s->len) {
        // Non-whitespace strtod can't advance through: either a token
        // cut at the chunk boundary (refill and retry) or garbage.
        if (s->eof || s->len - s->pos > kCarry)
          break; // unparsable / not a number: caller maps short count
        if (!tj_refill(s))
          break;
        continue;
      }
      // Pure-whitespace tail: drained, or pull the next chunk.
      if (s->eof || !tj_refill(s))
        break;
      continue;
    }
    // A token ending exactly at the buffer end may be truncated; refill
    // and re-parse it whole (unless the file is exhausted).  The clamped
    // refill can carry a tail up to the full buffer, so even tokens
    // longer than kCarry re-parse whole; only a single token filling the
    // ENTIRE buffer (> 1 MiB) degrades to accepting the split parse.
    if ((size_t)(end - s->buf) == s->len && !s->eof &&
        s->len - s->pos < kChunk + kCarry) {
      tj_refill(s);
      continue;
    }
    out[parsed++] = v;
    s->pos = end - s->buf;
  }
  return parsed;
}

void tj_stream_close(void *handle) {
  TjStream *s = (TjStream *)handle;
  if (s) {
    std::fclose(s->f);
    std::free(s->buf);
    delete s;
  }
}

// Write a matrix in the reference's format (row-major, whitespace
// separated) so files round-trip through the reference binary.
long tj_write_matrix_text(const char *path, const double *data, long rows,
                          long cols) {
  FILE *f = std::fopen(path, "wb");
  if (!f)
    return -1;
  for (long i = 0; i < rows; i++) {
    for (long j = 0; j < cols; j++) {
      if (std::fprintf(f, "%.17g%c", data[i * cols + j],
                       j + 1 == cols ? '\n' : ' ') < 0) {
        std::fclose(f);
        return -2; // write failure (e.g. disk full)
      }
    }
  }
  if (std::fclose(f) != 0)
    return -2; // buffered data lost on close
  return rows * cols;
}

} // extern "C"
