"""Headline benchmark: N x N fp32 Gauss-Jordan inversion on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): the reference MPI code inverts 4096x4096 fp64 at
~6.8 GFLOP/s on one CPU core (m=48, its best configuration).  We report
GFLOP/s (2n^3 / wall) for the same n on one TPU chip and the speedup
vs that 6.8 GFLOP/s.  The measured path is the in-place blocked
Gauss-Jordan (ops/jordan_inplace.py) at the tuned block size m=128
(benchmarks/PHASES.md) — same condition-based pivot rule as the reference.

Timing methodology: this environment tunnels to the TPU with ~100ms RTT and
a readback-pipelining quirk, so the inversion is repeated K times inside a
single jitted fori_loop (data-dependent chaining, no host round trips),
a scalar is read back once, and the run is measured at two different K so
constant offsets (RTT, dispatch) cancel in the slope.
"""

import json


def main():
    import jax.numpy as jnp

    from tpu_jordan.ops import (
        block_jordan_invert_inplace,
        generate,
        inf_norm,
        residual_inf_norm,
    )
    from tpu_jordan.utils.benchmarking import slope_time

    n, m = 4096, 128
    baseline_gflops = 6.8  # BASELINE.md, 4096x4096 fp64, m=48, 1 CPU core

    a = generate("absdiff", (n, n), jnp.float32)
    per_call = slope_time(
        lambda v: block_jordan_invert_inplace(v, block_size=m)[0],
        (a,), r1=8, r2=24,
    )

    # Sanity: the result must be a real inverse.
    inv, sing = block_jordan_invert_inplace(a, block_size=m)
    rel_res = float(residual_inf_norm(a, inv)) / float(inf_norm(a))
    assert not bool(sing), "benchmark matrix flagged singular"
    assert rel_res < 1e-3, f"benchmark inverse inaccurate: {rel_res}"

    gflops = 2.0 * n**3 / per_call / 1e9
    print(json.dumps({
        "metric": f"invert_{n}x{n}_f32_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / baseline_gflops, 1),
    }))


if __name__ == "__main__":
    main()
