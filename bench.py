"""Headline benchmark: N x N fp32 Gauss-Jordan inversion on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): the reference MPI code inverts 4096x4096 fp64 at
~6.8 GFLOP/s on one CPU core (m=48, its best configuration).  We report
GFLOP/s (2n^3 / wall) for the same n on one TPU chip and the speedup
vs that 6.8 GFLOP/s.

Timing methodology: this environment tunnels to the TPU with ~100ms RTT and
a readback-pipelining quirk, so the inversion is repeated K times inside a
single jitted fori_loop (data-dependent chaining, no host round trips) and
a scalar is read back once; tunnel RTT is measured separately and
subtracted.
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_jordan.ops import block_jordan_invert, generate, residual_inf_norm

    n, m, reps = 4096, 256, 4
    baseline_gflops = 6.8  # BASELINE.md, 4096x4096 fp64, m=48, 1 CPU core

    a = generate("absdiff", (n, n), jnp.float32)

    # Tunnel RTT calibration (scalar round trip).
    tiny = jax.jit(lambda x: jnp.sum(x) * 0)
    z = jnp.zeros((8, 8), jnp.float32)
    np.asarray(tiny(z))
    ts = []
    for _ in range(7):
        t0 = time.perf_counter()
        np.asarray(tiny(z))
        ts.append(time.perf_counter() - t0)
    rtt = float(np.median(ts))

    @jax.jit
    def many(a):
        def body(i, v):
            inv, _ = block_jordan_invert(v, block_size=m)
            return inv
        return jnp.sum(lax.fori_loop(0, reps, body, a))

    np.asarray(many(a))  # compile + warm
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(many(a))
        ts.append(time.perf_counter() - t0)
    per_call = (float(np.median(ts)) - rtt) / reps

    # Sanity: the result must be a real inverse.
    inv, sing = block_jordan_invert(a, block_size=m)
    from tpu_jordan.ops import inf_norm
    rel_res = float(residual_inf_norm(a, inv)) / float(inf_norm(a))
    assert not bool(sing), "benchmark matrix flagged singular"
    assert rel_res < 1e-3, f"benchmark inverse inaccurate: {rel_res}"

    gflops = 2.0 * n**3 / per_call / 1e9
    print(json.dumps({
        "metric": f"invert_{n}x{n}_f32_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / baseline_gflops, 1),
    }))


if __name__ == "__main__":
    main()
