"""Headline benchmark: N x N fp32 Gauss-Jordan inversion on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Baseline (BASELINE.md): the reference MPI code inverts fp64 at ~6.8
GFLOP/s on one CPU core (m=48, its best configuration, flat across
sizes).  We report GFLOP/s (2n^3 / wall) on one TPU chip and the speedup
vs that 6.8 GFLOP/s.  Two configs are captured (VERDICT r2 #3):

  * 4096^2, m=128 — the tuned single-chip headline (the primary metric);
  * 8192^2, m=256 — the BASELINE.md v4-8 north-star config (m=256 is
    the round-4 tuned block size: the composed-permutation unscramble
    removed the per-step copy tax that previously favored m=384, and
    the fused-panel probe applies; measured 78 ms vs 102 ms at m=384,
    same session).  The |i−j| fixture sits on an fp32 knife edge at
    n=8192 with m=256 (singular in some sessions, fine in others —
    benchmarks/PHASES.md): if the probe flags it, the row falls back to
    the always-safe m=384 and reports which config ran.

The measured path is the in-place blocked Gauss-Jordan
(ops/jordan_inplace.py) with the fused-panel pallas probe
(benchmarks/PHASES.md) — same condition-based pivot rule as the
reference.

Timing methodology: this environment tunnels to the TPU with ~100ms RTT
and a readback-pipelining quirk, so the inversion is repeated K times
inside a single jitted fori_loop (data-dependent chaining, no host round
trips), a scalar is read back once, and the run is measured at two
different K so constant offsets (RTT, dispatch) cancel in the slope.
"""

import json


class _Singular(AssertionError):
    pass


def _measure(n, m, r1, r2, generator="absdiff", max_rel=1e-2):
    from tpu_jordan.ops import (
        block_jordan_invert_inplace,
        generate,
        inf_norm,
        residual_inf_norm,
    )
    from tpu_jordan.utils.benchmarking import slope_time

    import jax.numpy as jnp

    a = generate(generator, (n, n), jnp.float32)
    per_call = slope_time(
        lambda v: block_jordan_invert_inplace(v, block_size=m)[0],
        (a,), r1=r1, r2=r2,
    )

    # Sanity: the result must be a real inverse.
    inv, sing = block_jordan_invert_inplace(a, block_size=m)
    rel_res = float(residual_inf_norm(a, inv)) / float(inf_norm(a))
    if bool(sing):
        raise _Singular(f"benchmark matrix flagged singular (n={n} m={m})")
    assert rel_res < max_rel, \
        f"benchmark inverse inaccurate: {rel_res} (n={n})"
    del a, inv

    return 2.0 * n**3 / per_call / 1e9, rel_res


def main():
    baseline_gflops = 6.8  # BASELINE.md: reference fp64, m=48, 1 CPU core

    gf_4096, rel_4096 = _measure(4096, 128, r1=8, r2=24)
    # 8192 row: m=256 (round-4 tuned), m=384 knife-edge fallback.
    m_8192 = 256
    try:
        gf_8192, rel_8192 = _measure(8192, m_8192, r1=3, r2=9)
    except _Singular:
        m_8192 = 384
        gf_8192, rel_8192 = _measure(8192, m_8192, r1=3, r2=9)
    extra = {
        f"invert_8192x8192_f32_m{m_8192}_gflops": round(gf_8192, 1),
        "vs_baseline_8192": round(gf_8192 / baseline_gflops, 1),
        "rel_residual_4096": f"{rel_4096:.1e}",
        "rel_residual_8192": f"{rel_8192:.1e}",
    }
    # Scale point, best-effort (the two contract configs above must never
    # be lost to a failure here): |i−j| genuinely exceeds fp32 at
    # n=16384 (PHASES.md), so this row uses the deterministic
    # well-conditioned 'rand' fixture.
    try:
        gf_16384, rel_16384 = _measure(16384, 256, r1=2, r2=5,
                                       generator="rand", max_rel=2e-1)
        extra["invert_16384_f32_m256_rand_gflops"] = round(gf_16384, 1)
        extra["vs_baseline_16384"] = round(gf_16384 / baseline_gflops, 1)
        extra["rel_residual_16384"] = f"{rel_16384:.1e}"
    except Exception as e:                      # noqa: BLE001
        extra["invert_16384_error"] = str(e)[:200]

    print(json.dumps({
        "metric": "invert_4096x4096_f32_gflops",
        "value": round(gf_4096, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gf_4096 / baseline_gflops, 1),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
