"""Headline benchmark: N x N fp32 Gauss-Jordan inversion on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Baseline (BASELINE.md): the reference MPI code inverts fp64 at ~6.8
GFLOP/s on one CPU core (m=48, its best configuration, flat across
sizes).  We report GFLOP/s (2n^3 / wall) on one TPU chip and the speedup
vs that 6.8 GFLOP/s.  Two configs are captured (VERDICT r2 #3):

  * 4096^2, m=128 — the tuned single-chip headline (the primary metric);
  * batched tiers (ISSUE 3): 512x512^2 m=128 (the dedicated batch-first
    engine) and the largest-fitting Bx2048^2 tier, with per-element
    singular counts and element-0 residual gates — the BASELINE.md
    batch north star's driver-captured rows (VERDICT r5 item 5);
  * 8192^2, m=256 — the BASELINE.md v4-8 north-star config (m=256 is
    the round-4 tuned block size: the composed-permutation unscramble
    removed the per-step copy tax that previously favored m=384, and
    the fused-panel probe applies; measured 78 ms vs 102 ms at m=384,
    same session).  The |i−j| fixture sits on an fp32 knife edge at
    n=8192 with m=256 (singular in some sessions, fine in others —
    benchmarks/PHASES.md): if the probe flags it, the row falls back to
    the always-safe m=384 and reports which config ran.

Accuracy gates (VERDICT r3 #3): every row reports its relative residual
‖A·X−I‖∞/‖A‖∞ next to the *predicted* backward-stability bound
eps·n·κ∞/‖A‖∞ (κ∞ = ‖A‖∞‖X‖∞ from exact row sums,
ops/norms.condition_inf).  The fixed-tolerance rows keep their
historical gate; the 16384 scale row gates on BOTH
  (a) the dynamic bound — rel residual < 3× predicted — and
  (b) Newton–Schulz CONTRACTION: one NS step must shrink the residual
      ≥ 2× (measured on chip: 1.4e-2 → 1.2e-3, 12×).
(b) is the airtight part: NS converges only from ‖I−AX‖∞ < 1, so a
genuinely wrong inverse cannot contract no matter how loose (a) is
(measured κ∞ of the rand fixture at 16384 is 1.07e7, which makes the
worst-case eps·n·κ bound ~2.5 — formally satisfied but 180× above the
measured residual; the n-linear growth factor simply doesn't
materialize, so contraction is the evidence that discriminates).

The measured path is the in-place blocked Gauss-Jordan
(ops/jordan_inplace.py) with the fused-panel pallas probe
(benchmarks/PHASES.md) — same condition-based pivot rule as the
reference.

FLOP accounting (ISSUE 10): the headline GFLOP/s keeps the hand 2n³
convention — changing the unit would orphan the BENCH_r01+ trajectory
and the 6.8 GFLOP/s baseline — but every row now ALSO records the
compiled executable's own ``cost_analysis()`` numbers
(``*_xla_flops`` / ``*_xla_gflops`` / ``*_xla_vs_2n3`` /
``*_arithmetic_intensity``, the arXiv:2112.09017 accounting
discipline; ``tpu_jordan/obs/hwcost.py``), plus an ``env`` fingerprint
(jax/jaxlib versions, device kind, host cores) so cross-round
comparisons — and the ``tools/check_bench.py`` regression sentinel —
are interpretable.

Timing methodology: this environment tunnels to the TPU with ~100ms RTT
and a readback-pipelining quirk, so the inversion is repeated K times
inside a single jitted fori_loop (data-dependent chaining, no host round
trips), a scalar is read back once, and the run is measured at two
different K so constant offsets (RTT, dispatch) cancel in the slope.
Since ISSUE 2 the per-row statistics (median-of-k slope samples, IQR
outlier rejection, variance_flag, typed transient retry) come from the
shared robust core in tpu_jordan/tuning/measure.py — the same one the
autotuner uses — instead of a private median-of-3.
"""

import json


class _Singular(AssertionError):
    pass


def _retry_transient(fn):
    """One retry on the documented-transient remote-compile failure class
    — the TYPED classifier and the one shared backoff implementation
    live in tpu_jordan/resilience/policy.py (RetryPolicy; ISSUE 5
    satellite — shared with the autotuner's measurement core) so
    bench.py can't fork its own weaker rule.  Anything non-transient —
    including the knife-edge _Singular (an AssertionError, never a
    runtime/transport type) — is a real result and propagates
    immediately; retries land in tpu_jordan_retries_total."""
    from tpu_jordan.resilience.policy import retry_transient

    return retry_transient(fn)


def _aot_first_call(fn, a):
    """ONE compile-inclusive first call (the ISSUE 4 row policy:
    recorded NEXT TO the steady-state slope so compile-time changes
    can't masquerade as execution regressions), AOT-lowered so the row
    also carries the executable's OWN cost_analysis accounting
    (ISSUE 10) — same trace+compile+run total as a jit-cache first
    call, zero extra compiles.  Returns ``((result, cost), span)``;
    the executable reference is dropped before returning."""
    import jax

    from tpu_jordan.obs import hwcost as _hwcost
    from tpu_jordan.obs.spans import timed_blocking

    def _first():
        compiled = jax.jit(fn).lower(a).compile()
        return compiled, compiled(a)

    (compiled, out), sp = timed_blocking(
        _first, name="first_call_compile_inclusive")
    cost = _hwcost.executable_cost(compiled)
    del compiled
    return (out, cost), sp


def _measure(n, m, r1, r2, generator="absdiff", max_rel=1e-2, refine=0,
             group=0, fori=False, pallas=False, mode="fp32"):
    """Returns (gflops, acc) with acc = {rel_residual, kappa,
    predicted_bound[, rel_residual_refine1]}.

    ``max_rel=None`` gates at 3× the predicted eps·n·κ∞ bound instead of
    a static tolerance.  ``refine=1`` also reports the residual after one
    Newton–Schulz step (not timed — an accuracy diagnostic, not a perf
    row).  ``group=k`` uses the delayed-group-update engine (the
    measured winner for well-conditioned fixtures at m=128 once the
    probe's launch cost dropped — benchmarks/PHASES.md round 4);
    ``fori=True`` takes its fori_loop twin (bit-identical inner
    arithmetic, compile cost flat in Nr — seconds instead of 88 s at
    Nr=128, shrinking the transient-failure exposure window).

    ``pallas=True`` takes the fused-Pallas-update grouped engine
    (ops/pallas_update.py, ISSUE 6): the group-closing normalize +
    eliminate sweep as one VMEM-resident kernel pass; ``mode="bf16"``
    is its bf16-compute/fp32-accumulate variant, whose dynamic
    eps·n·κ gate is judged at bf16 eps — bf16-grade residuals on a
    well-conditioned fixture are the contract, not a failure (the
    product path guards them with the residual-gate ladder; the bench
    row gates explicitly).  The NS contraction assert is UNCHANGED in
    bf16 mode: refinement runs at fp32 HIGHEST regardless, so the
    ≥2x-contraction requirement and the fp32-attainable 2e-3 floor
    still apply to the refined residual.
    """
    from functools import partial

    from tpu_jordan.ops import (
        block_jordan_invert_inplace,
        block_jordan_invert_inplace_grouped,
        block_jordan_invert_inplace_grouped_fori,
        block_jordan_invert_inplace_grouped_pallas,
        condition_inf,
        generate,
        inf_norm,
        newton_schulz,
        residual_inf_norm,
    )
    from tpu_jordan.tuning.measure import measure_slope

    import numpy as np

    import jax.numpy as jnp

    if pallas:
        engine = partial(block_jordan_invert_inplace_grouped_pallas,
                         group=group or 2, mode=mode)
    elif group:
        grouped = (block_jordan_invert_inplace_grouped_fori if fori
                   else block_jordan_invert_inplace_grouped)
        engine = partial(grouped, group=group)
    else:
        engine = block_jordan_invert_inplace

    a = generate(generator, (n, n), jnp.float32)
    # Invert ONCE before the timing campaign: the knife-edge fallback
    # (_Singular) must fire from this cheap call, not after r2 timed
    # repetitions of a result that would be discarded.
    ((inv, sing), cost), first_sp = _aot_first_call(
        lambda v: engine(v, block_size=m), a)
    if bool(sing):
        raise _Singular(f"benchmark matrix flagged singular (n={n} m={m})")
    # The robust measurement core (tuning/measure.py, shared with the
    # autotuner): median of 3 in-session slope samples on one compiled
    # executable plus an explicit variance flag (VERDICT r5 weak #1: a
    # single unguarded sample silently regressed the 4096 headline 15%
    # on session noise).  At k=3 the median is the outlier damper and a
    # wild sample trips the flag via the spread; the Tukey fence only
    # gains teeth at k>=5 (measure.py) — bench keeps k=3 because each
    # extra slope sample costs two full timed ladders on the chip.
    meas = measure_slope(
        lambda v: engine(v, block_size=m)[0],
        (a,), r1=r1, r2=r2, samples=3,
    )
    per_call = meas.seconds

    norm_a = float(inf_norm(a))
    rel_res = float(residual_inf_norm(a, inv)) / norm_a
    kappa = float(condition_inf(a, inv))
    # The eps·n·κ∞ backward-stability bound expressed in the same
    # ‖A‖∞-relative scale as rel_res: ‖AX−I‖ ≲ c·eps·n·‖A‖‖X‖, so
    # rel_res ≲ c·eps·n·κ∞/‖A‖∞ (= eps·n·‖X‖∞).  Measured c across
    # fixtures and sizes is 0.1–0.4, so the 3× dynamic gate is ~10–30×
    # tighter than it sounds and fails a genuinely wrong inverse.
    # The backward-stability bound is judged at the COMPUTE precision:
    # bf16 rows predict eps_bf16·n·κ (the fp32-accumulate recipe's
    # operand rounding is the error source, arXiv:2112.09017).
    eps_gate = (float(jnp.finfo(jnp.bfloat16).eps) if mode == "bf16"
                else float(np.finfo(np.float32).eps))
    predicted = eps_gate * n * kappa / norm_a
    # The dynamic gate is capped at 0.5: at n=16384 the worst-case
    # eps·n·κ bound is ~2.5 — trivially satisfiable on its own — and a
    # rel residual >= 0.5 means ‖I−AX‖ ≈ ‖I‖, i.e. no inverse at all,
    # whatever κ claims.  The NS contraction check remains the airtight
    # gate; this ceiling keeps (a) non-vacuous even when refine=0.
    gate = min(3.0 * predicted, 0.5) if max_rel is None else max_rel
    assert rel_res < gate, (
        f"benchmark inverse inaccurate: rel_residual={rel_res} exceeds "
        f"gate={gate:.3e} (predicted eps*n*kappa={predicted:.3e}, "
        f"kappa={kappa:.3e}, n={n})"
    )
    gf = lambda t: 2.0 * n**3 / t / 1e9           # noqa: E731
    acc = {
        "rel_residual": f"{rel_res:.1e}",
        "kappa": f"{kappa:.3e}",
        "predicted_bound": f"{predicted:.1e}",
        # Robust capture record (IQR-accepted samples): [min, max]
        # GFLOP/s around the median-of-record, the spread, how many
        # samples the Tukey fence rejected, and — when the spread
        # exceeds 10% — an explicit variance_flag so a noisy session
        # can't masquerade as a code regression (or improvement).
        "gflops_minmax": [round(gf(max(meas.accepted)), 1),
                          round(gf(min(meas.accepted)), 1)],
        "spread_pct": meas.spread_pct,
        # Compile vs execute separated (ISSUE 4): the first call pays
        # trace+compile+one inversion; the steady state is the slope
        # per-call on the cached executable.
        "first_call_compile_inclusive_s": round(first_sp.duration, 3),
        "steady_state_s": round(per_call, 6),
    }
    if meas.rejected:
        acc["iqr_rejected_samples"] = len(meas.rejected)
    if meas.variance_flag:
        acc["variance_flag"] = meas.variance_flag
    # The compiled executable's OWN accounting next to the hand
    # convention (ISSUE 10: the arXiv:2112.09017 discipline — achieved
    # rates attributed from compiler-counted flops, the hand 2n³
    # headline kept ONLY for cross-round/BASELINE comparability).
    # Absent when the backend exposes no analysis — never modeled.
    if cost.available and cost.flops:
        acc["xla_flops"] = cost.flops
        acc["xla_gflops"] = round(cost.flops / per_call / 1e9, 1)
        acc["xla_vs_2n3"] = round(cost.flops / (2.0 * n**3), 3)
        ai = cost.arithmetic_intensity
        if ai is not None:
            acc["arithmetic_intensity"] = round(ai, 1)
    # Accounting-class capacity field (ISSUE 13 satellite): the
    # executable's memory_analysis HBM footprint — excluded from the
    # cross-round perf comparison by check_bench (a jaxlib layout
    # change must not page as an execution regression).
    if cost.available and cost.hbm_bytes is not None:
        acc["peak_hbm_bytes"] = cost.hbm_bytes
    if refine:
        refined = newton_schulz(a, inv, refine)
        rel_ref = float(residual_inf_norm(a, refined)) / norm_a
        acc[f"rel_residual_refine{refine}"] = f"{rel_ref:.1e}"
        del refined
        # Contraction gate: NS only converges from ‖I−AX‖∞ < 1, so a
        # wrong inverse cannot pass this regardless of how pessimistic
        # the eps·n·κ bound is (see module docstring).  The 2e-3 floor is
        # the already-converged escape: one step cannot halve a residual
        # already at the fp32 attainable floor (~1.2e-3 measured at
        # 16384), and anything below the floor is unimpeachably a real
        # inverse.
        assert rel_ref < max(0.5 * rel_res, 2e-3), (
            f"Newton–Schulz failed to contract ({rel_res} -> {rel_ref}): "
            f"the computed inverse is not a convergent approximation "
            f"(n={n})"
        )
    del a, inv

    return 2.0 * n**3 / per_call / 1e9, acc


def _capture_ladder(extra, n, tiers, r1, r2, baseline_gflops, vs_key):
    """Run a scale row's capture ladder: each tier retries once on the
    transient remote-compile failure class; a knife-edge _Singular in a
    grouped tier skips its bit-identical fori twin (a deterministic
    outcome — don't pay its compile+invert); the first tier that lands
    becomes the row of record.  Returns (gf, acc) or (None, None)."""
    skip_grouped = False
    for cfg, mm, kw in tiers:
        if skip_grouped and kw.get("group"):
            extra[f"invert_{n}_{cfg}_error"] = "skipped: singular twin"
            continue
        try:
            gf, acc = _retry_transient(
                lambda: _measure(n, mm, r1=r1, r2=r2, generator="rand",
                                 max_rel=None, refine=1, **kw))
        except _Singular as ge:
            extra[f"invert_{n}_{cfg}_error"] = str(ge)[:200]
            skip_grouped = bool(kw.get("group"))
            continue
        except Exception as ge:                 # noqa: BLE001
            extra[f"invert_{n}_{cfg}_error"] = str(ge)[:200]
            continue
        extra[f"invert_{n}_f32_{cfg}_rand_gflops"] = round(gf, 1)
        extra[vs_key] = round(gf / baseline_gflops, 1)
        return gf, acc
    return None, None


def _record_spread(extra, prefix, acc):
    """Robust-capture bookkeeping per headline row: [min, max] GFLOP/s
    over the IQR-accepted samples, spread %, rejected-sample count, and
    the explicit >10% variance_flag (VERDICT r5 weak #1)."""
    extra[f"{prefix}_gflops_minmax"] = acc["gflops_minmax"]
    extra[f"{prefix}_spread_pct"] = acc["spread_pct"]
    # Optional because _batched_row records its compile/steady split
    # directly into extra and passes a spread-only dict here.
    if "first_call_compile_inclusive_s" in acc:
        extra[f"{prefix}_first_call_compile_inclusive_s"] = (
            acc["first_call_compile_inclusive_s"])
        extra[f"{prefix}_steady_state_s"] = acc["steady_state_s"]
    if "iqr_rejected_samples" in acc:
        extra[f"{prefix}_iqr_rejected_samples"] = acc["iqr_rejected_samples"]
    if "variance_flag" in acc:
        extra[f"{prefix}_variance_flag"] = acc["variance_flag"]
    # Compiler-counted accounting (ISSUE 10/13), when the backend gave
    # it; the *_bytes keys are accounting-class — never compared
    # across rounds (tools/check_bench.py).
    for key in ("xla_flops", "xla_gflops", "xla_vs_2n3",
                "arithmetic_intensity", "peak_hbm_bytes"):
        if key in acc:
            extra[f"{prefix}_{key}"] = acc[key]


def _batched_row(extra, B, n, m, r1, r2, baseline_gflops, label):
    """One batched capture row (VERDICT r5 item 5: the batch north star
    had ZERO driver-captured numbers): B generated n² matrices through
    ``ops.batched.batched_jordan_invert`` (the dedicated batch-first
    engine in its validated small-n regime, the fori route beyond),
    slope-timed on the robust core, with per-element singular counts
    and an element-0 residual gate (3× the predicted eps·n·κ∞ bound,
    capped at 0.5 — the same dynamic gate as the scale rows).

    Returns the per-call seconds, or None (error keys recorded)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tpu_jordan.driver import batch_metrics
    from tpu_jordan.ops import batched_jordan_invert, generate
    from tpu_jordan.tuning.measure import measure_slope

    # The solve_batch fixture convention: per-element index offsets give
    # distinct matrices under the 'rand' generator.
    offs = jnp.arange(B, dtype=jnp.int32) * n
    a = jax.jit(jax.vmap(
        lambda o: generate("rand", (n, n), jnp.float32, row_offset=o,
                           col_offset=o)
    ))(offs)
    # Compile-inclusive first call recorded next to the steady-state
    # slope (the shared _aot_first_call bracket — same policy as
    # _measure, cost_analysis included).
    ((inv, sing), cost), first_sp = _aot_first_call(
        lambda v: batched_jordan_invert(v, block_size=m), a)
    extra[f"batched_{label}_first_call_compile_inclusive_s"] = round(
        first_sp.duration, 3)
    nsing = int(jnp.sum(sing))
    extra[f"batched_{label}_singular"] = f"{nsing}/{B}"
    if nsing:
        raise _Singular(f"batched fixture flagged singular ({nsing}/{B} "
                        f"elements, B={B} n={n} m={m})")
    met = batch_metrics(a[:1], inv[:1])
    rel0 = float(met["rel_residual"][0])
    kappa0 = float(met["kappa"][0])
    norm0 = float(met["norm_a"][0])
    predicted = float(np.finfo(np.float32).eps) * n * kappa0 / norm0
    gate = min(3.0 * predicted, 0.5)
    assert rel0 < gate, (
        f"batched inverse inaccurate: rel_residual[0]={rel0} exceeds "
        f"gate={gate:.3e} (kappa={kappa0:.3e}, B={B}, n={n})")
    del inv
    meas = measure_slope(
        lambda v: batched_jordan_invert(v, block_size=m)[0], (a,),
        r1=r1, r2=r2, samples=3)
    gf = 2.0 * n**3 * B / meas.seconds / 1e9
    extra[f"batched_{label}_steady_state_s"] = round(meas.seconds, 6)
    extra[f"batched_{label}_f32_gflops"] = round(gf, 1)
    if cost.available and cost.flops:
        extra[f"batched_{label}_xla_flops"] = cost.flops
        extra[f"batched_{label}_xla_gflops"] = round(
            cost.flops / meas.seconds / 1e9, 1)
    extra[f"batched_{label}_vs_baseline"] = round(gf / baseline_gflops, 1)
    extra[f"batched_{label}_rel_residual0"] = f"{rel0:.1e}"
    extra[f"batched_{label}_kappa0"] = f"{kappa0:.3e}"
    _record_spread(extra, f"batched_{label}",
                   {"gflops_minmax": [
                       round(2.0 * n**3 * B / max(meas.accepted) / 1e9, 1),
                       round(2.0 * n**3 * B / min(meas.accepted) / 1e9, 1)],
                    "spread_pct": meas.spread_pct,
                    **({"iqr_rejected_samples": len(meas.rejected)}
                       if meas.rejected else {}),
                    **({"variance_flag": meas.variance_flag}
                       if meas.variance_flag else {})})
    return meas.seconds


def _batched_rows(extra, baseline_gflops):
    """The batch north-star captures (best-effort — a failure records an
    error key, never loses the single-matrix rows):

      * 512×512², m=128 — the dedicated small-n batch-first engine
        (Nr=4, B >= 32: its validated regime, measured 1,602 GF/s in
        the round-5 session);
      * the largest-fitting B×2048² tier (BASELINE.md batch north star
        is 512×2048² on a v5p-64; one v5e chip fits a B ladder probed
        largest-first, fori route).
    """
    try:
        _retry_transient(lambda: _batched_row(
            extra, 512, 512, 128, r1=2, r2=6,
            baseline_gflops=baseline_gflops, label="512x512"))
    except Exception as ge:                     # noqa: BLE001
        extra["batched_512x512_error"] = str(ge)[:200]
    for B in (64, 32, 16, 8):
        try:
            _retry_transient(lambda: _batched_row(
                extra, B, 2048, 128, r1=1, r2=3,
                baseline_gflops=baseline_gflops, label=f"{B}x2048"))
            extra["batched_2048_tier"] = B
            return
        except AssertionError as ge:
            # Deterministic fixture verdict (_Singular or the element-0
            # accuracy gate — element 0 is offset-0 regardless of B):
            # shrinking B cannot change it, stop the ladder.
            extra[f"batched_{B}x2048_error"] = str(ge)[:200]
            return
        except Exception as ge:                 # noqa: BLE001
            # OOM/compile failure at this tier: record and try smaller.
            extra[f"batched_{B}x2048_error"] = str(ge)[:200]


def _sharded_swapfree_row(extra):
    """Sharded-output (gather=False) capture: the swap-free engine with
    its bucketed-ppermute permutations keeps the inverse block-sharded
    end to end (VERDICT r5 missing #1).  This bench host exposes ONE
    chip, so the leg runs on a forced 8-virtual-device CPU mesh in a
    subprocess (the __graft_entry__ dryrun recipe) — the row evidences
    the memory-contract path (relative residual + per-shard bytes =
    exactly 1/8 of the matrix); its elapsed is CPU-mesh wall time and
    is never compared to the chip baseline.

    ISSUE 14: the row also carries the communication observatory's
    numbers — ``*_comm_bytes`` (the layout-exact elimination-section
    collective payload, an ACCOUNTING field check_bench never compares
    across rounds: a layout/dtype change re-prices the same solve) and
    ``*_comm_gbps`` (achieved interconnect GB/s = modeled wire bytes
    over the measured non-compute residue — a RATE the sentinel pages
    on like any ``*_gflops`` shortfall; the mesh bandwidth sentinel)."""
    import subprocess
    import sys

    from __graft_entry__ import _REPO, _cpu_env

    child = (
        "import jax, json\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from tpu_jordan.driver import solve\n"
        "n, m = 2048, 128\n"
        "r = solve(n, m, workers=(2, 4), engine='swapfree', gather=False)\n"
        "b = r.inverse_blocks\n"
        "shard = max(s.data.nbytes for s in b.addressable_shards)\n"
        "assert r.inverse is None and shard * 8 == b.nbytes\n"
        "d = r.comm.drift or {}\n"
        "wt = r.work.to_json()['totals']\n"
        "print(json.dumps({'n': n, 'm': m, 'mesh': '2x4',\n"
        "                  'engine': 'swapfree', 'gather': False,\n"
        "                  'elapsed_s': round(r.elapsed, 3),\n"
        "                  'rel_residual': f'{r.rel_residual:.1e}',\n"
        "                  'per_shard_mib': round(shard / 2**20, 2),\n"
        "                  'comm_payload_bytes': int(sum(\n"
        "                      s.payload_bytes * s.executed\n"
        "                      for s in r.comm.sigs\n"
        "                      if s.section == 'engine')),\n"
        "                  'comm_gbps': d.get('achieved_gbps'),\n"
        "                  'comm_vs_projected':\n"
        "                      d.get('comm_vs_projected'),\n"
        "                  'work_skew': wt['skew'],\n"
        "                  'work_ragged_penalty':\n"
        "                      wt['ragged_penalty']}))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", child], env=_cpu_env(8), cwd=_REPO,
            capture_output=True, text=True, timeout=900, check=True)
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        row["note"] = "cpu-mesh memory-contract leg, not chip throughput"
        extra["sharded_swapfree_gather_false"] = row
        # Top-level sentinel keys (tools/check_bench.py): the bytes key
        # is accounting-class (never compared cross-round); the GB/s
        # key is a rate — a quiet shortfall pages like a gflops one.
        extra["sharded_swapfree_2048_comm_bytes"] = row[
            "comm_payload_bytes"]
        if row.get("comm_gbps") is not None:
            extra["sharded_swapfree_2048_comm_gbps"] = round(
                row["comm_gbps"], 4)
        # ISSUE 19: work-observatory accounting fields (layout-exact
        # imbalance factor + padding penalty — never compared
        # cross-round: a layout change re-prices the same solve).
        extra["sharded_swapfree_2048_work_skew"] = row["work_skew"]
        extra["sharded_swapfree_2048_ragged_penalty"] = row[
            "work_ragged_penalty"]
    except Exception as e:                      # noqa: BLE001
        extra["sharded_swapfree_gather_false_error"] = str(e)[:200]


def _solve_sharded_row(extra, n=4096, m=128, p=8, ks=(1, 8, 32),
                       timeout=1800):
    """ISSUE 15 capture row ``solve_sharded_4096`` extended into the
    ISSUE 17 multi-RHS blocking study: the distributed [A | B]
    elimination on a 1D p=8 mesh swept over the RHS block width
    (``solve_sharded_4096_k{1,8,32}_*`` — the JAXMg blocking question
    measured on the sharded solve path).  This bench host exposes ONE
    chip, so every leg runs on a forced 8-virtual-device CPU mesh in a
    subprocess (the __graft_entry__ dryrun recipe) — elapsed is
    CPU-mesh wall time; each leg's evidence is the backward-error
    gate, the executable's own ``cost_analysis`` FLOPs
    (``*_xla_flops``, accounting-class) and the communication
    observatory's numbers: ``*_comm_bytes`` (layout-exact
    elimination-section payload, accounting-class — never compared
    cross-round) and ``*_comm_gbps`` (achieved GB/s — a RATE the
    sentinel pages on, the mesh bandwidth sentinel).  GFLOP/s uses the
    workload-aware n³(1+k/n) convention with median-of-3 spread per
    leg.  The k=8 leg keeps the historical key names
    (``solve_sharded_4096`` / ``*_comm_bytes`` / ``*_comm_gbps``) so
    the cross-round trajectory never diffs a renamed config against
    itself.  One failing k-leg records its own error key and never
    loses the siblings."""
    import subprocess
    import sys

    from __graft_entry__ import _REPO, _cpu_env

    stem = f"solve_sharded_{n}"
    child = (
        "import jax, json\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from tpu_jordan.linalg import solve_system\n"
        "from tpu_jordan.linalg.api import solve_mesh_backend\n"
        "from tpu_jordan.obs import hwcost as _hwcost\n"
        "from tpu_jordan.ops import generate\n"
        "from tpu_jordan.tuning.measure import measure_direct\n"
        "import jax.numpy as jnp\n"
        f"n, m, p, ks = {n}, {m}, {p}, {list(ks)!r}\n"
        "a = generate('rand', (n, n), jnp.float32)\n"
        "out = {}\n"
        "for k in ks:\n"
        "    try:\n"
        "        b = generate('rand', (n, k), jnp.float32,\n"
        "                     row_offset=n)\n"
        "        r = solve_system(a, b, block_size=m, workers=p,\n"
        "                         engine='solve_sharded')\n"
        "        assert r.engine == 'solve_sharded', r.engine\n"
        "        mesh, lay, sc_a, sc_b, compile_fn, _ = \\\n"
        "            solve_mesh_backend(p, n, m)\n"
        "        W = sc_a(a, lay, mesh); X = sc_b(b, lay, mesh)\n"
        "        run = compile_fn(W, X, mesh, lay)\n"
        "        meas = measure_direct(\n"
        "            lambda: jax.block_until_ready(run(W, X)[0]),\n"
        "            samples=3, warmup=1)\n"
        "        flops = _hwcost.baseline_workload_flops(n, 'solve',\n"
        "                                                k=k)\n"
        "        d = r.comm.drift or {}\n"
        "        leg = {'k': k,\n"
        "               'elapsed_s': round(meas.seconds, 3),\n"
        "               'gflops': round(flops / meas.seconds / 1e9, 1),\n"
        "               'spread_pct': meas.spread_pct,\n"
        "               'variance_flag': meas.variance_flag,\n"
        "               'rel_backward_error': r.rel_residual,\n"
        "               'comm_payload_bytes': int(sum(\n"
        "                   s.payload_bytes * s.executed\n"
        "                   for s in r.comm.sigs\n"
        "                   if s.section == 'engine')),\n"
        "               'comm_gbps': d.get('achieved_gbps'),\n"
        "               'comm_vs_projected': d.get('comm_vs_projected'),\n"
        "               'work_skew': r.work.to_json()['totals'][\n"
        "                   'skew'],\n"
        "               'work_ragged_penalty': r.work.to_json()[\n"
        "                   'totals']['ragged_penalty']}\n"
        "        try:\n"
        "            c = _hwcost.executable_cost(run)\n"
        "            if c.available and c.flops:\n"
        "                leg['xla_flops'] = c.flops\n"
        "        except Exception:\n"
        "            pass\n"
        "        out['k%d' % k] = leg\n"
        "    except Exception as e:\n"
        "        out['k%d' % k] = {'error': str(e)[:200]}\n"
        "print(json.dumps({'n': n, 'm': m, 'mesh': 'p%d' % p,\n"
        "                  'legs': out}))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", child], env=_cpu_env(p), cwd=_REPO,
            capture_output=True, text=True, timeout=timeout, check=True)
        doc = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:                      # noqa: BLE001
        extra[f"{stem}_error"] = str(e)[:200]
        return
    legs = doc.get("legs", {})
    for k in ks:
        leg = legs.get(f"k{k}") or {}
        if "gflops" not in leg:
            extra[f"{stem}_k{k}_error"] = str(
                leg.get("error", "no capture"))[:200]
            continue
        extra[f"{stem}_k{k}_gflops"] = leg["gflops"]
        extra[f"{stem}_k{k}_spread_pct"] = leg["spread_pct"]
        if leg.get("variance_flag"):
            extra[f"{stem}_k{k}_variance_flag"] = leg["variance_flag"]
        extra[f"{stem}_k{k}_rel_backward_error"] = leg[
            "rel_backward_error"]
        # Sentinel classes (tools/check_bench.py): bytes + xla_flops =
        # accounting (never compared cross-round), GB/s = rate (pages
        # on quiet shortfalls) — the ISSUE 14 convention.
        extra[f"{stem}_k{k}_comm_bytes"] = leg["comm_payload_bytes"]
        if leg.get("xla_flops"):
            extra[f"{stem}_k{k}_xla_flops"] = leg["xla_flops"]
        # ISSUE 19: work-observatory accounting fields (layout-exact,
        # never compared cross-round).
        extra[f"{stem}_k{k}_work_skew"] = leg["work_skew"]
        extra[f"{stem}_k{k}_ragged_penalty"] = leg["work_ragged_penalty"]
        if k != 8 and leg.get("comm_gbps") is not None:
            extra[f"{stem}_k{k}_comm_gbps"] = round(leg["comm_gbps"], 4)
    # The historical k=8 row + legacy sentinel keys (unchanged names —
    # the trajectory must keep comparing like-for-like by key).
    k8 = legs.get("k8") or {}
    if "gflops" in k8:
        row = dict(k8)
        row.update(n=n, m=m, mesh=f"p{p}", engine="solve_sharded",
                   note=("cpu-mesh distributed-solve leg, not chip "
                         "throughput; flops convention n^3*(1+k/n)"))
        extra[stem] = row
        extra[f"{stem}_comm_bytes"] = k8["comm_payload_bytes"]
        if k8.get("comm_gbps") is not None:
            extra[f"{stem}_comm_gbps"] = round(k8["comm_gbps"], 4)


def _lookahead_row(extra, n=4096, m=128):
    """ISSUE 16 capture row ``lookahead_4096``: the single-chip
    probe-ahead engine (panel-first eliminate, step t+1's condition
    probe before the trailing update) at the headline size, standard
    robust capture (median-of-3, spread %, variance flag), the
    executable's own ``cost_analysis`` accounting, and the dynamic
    eps·n·κ∞ residual gate.  The row also records the cost model's
    probe-overlap headroom as ``lookahead_4096_overlap_frac`` — an
    ACCOUNTING field (tools/check_bench.py never compares it across
    rounds: a comm-model re-weighting re-prices the same schedule);
    the rate key the sentinel pages on is the ``*_gflops`` one.  On one
    chip probe and GEMM share the compute units, so parity with
    ``invert_4096`` is the expectation — the row exists to catch the
    schedule costing anything before TPU capture, where the hidden
    cross-worker probe reduction is the payoff."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_jordan.obs import hwcost as _hwcost
    from tpu_jordan.ops import (condition_inf, generate,
                                residual_inf_norm)
    from tpu_jordan.ops.jordan_inplace import (
        block_jordan_invert_inplace_lookahead,
    )
    from tpu_jordan.tuning.measure import measure_direct
    from tpu_jordan.tuning.registry import (TunePoint,
                                            probe_overlap_headroom)

    label = f"lookahead_{n}"
    try:
        a = generate("rand", (n, n), jnp.float32)
        compiled = jax.jit(
            lambda aa, _m=m: block_jordan_invert_inplace_lookahead(
                aa, block_size=_m)
        ).lower(a).compile()
        cost = _hwcost.executable_cost(compiled)
        inv, sing = compiled(a)
        jax.block_until_ready(inv)
        if bool(sing):
            raise _Singular(f"{label}: fixture flagged singular")
        kappa = float(condition_inf(a, inv))
        rel = float(residual_inf_norm(a, inv)
                    / jnp.max(jnp.sum(jnp.abs(a), axis=1)))
        bound = 3.0 * float(jnp.finfo(jnp.float32).eps) * n * kappa
        if not rel <= min(bound, 0.5):   # raised, not asserted
            raise _Singular(f"{label}: residual {rel:.2e} > gate "
                            f"{min(bound, 0.5):.2e}")

        def call(_c=compiled, _a=a):
            jax.block_until_ready(_c(_a)[0])

        meas = _retry_transient(
            lambda: measure_direct(call, samples=3, warmup=1))
        flops = _hwcost.baseline_workload_flops(n, "invert")
        gfs = sorted(flops / s / 1e9 for s in meas.accepted)
        extra[f"{label}_gflops"] = round(flops / meas.seconds / 1e9, 1)
        extra[f"{label}_gflops_minmax"] = [round(gfs[0], 1),
                                           round(gfs[-1], 1)]
        extra[f"{label}_spread_pct"] = meas.spread_pct
        if meas.variance_flag:
            extra[f"{label}_variance_flag"] = meas.variance_flag
        extra[f"{label}_rel_residual"] = rel
        extra[f"{label}_kappa"] = kappa
        pt = TunePoint.create(n, m, jnp.float32, 1, True)
        extra[f"{label}_overlap_frac"] = float(
            f"{probe_overlap_headroom(pt):.4g}")
        if cost.available and cost.flops:
            extra[f"{label}_xla_flops"] = cost.flops
            if meas.seconds > 0:
                extra[f"{label}_xla_gflops"] = round(
                    cost.flops / meas.seconds / 1e9, 1)
    except Exception as e:                      # noqa: BLE001
        extra[f"{label}_error"] = str(e)[:200]


def _solve_lookahead_sharded_row(extra):
    """ISSUE 16 capture row ``solve_lookahead_sharded_4096``: the
    probe-ahead distributed [A | B] elimination (k=8 RHS, 1D p=8),
    the subprocess CPU-mesh recipe of ``_solve_sharded_row`` — elapsed
    is CPU-mesh wall time, never chip throughput.  The child also
    bit-compares X against engine='solve_sharded' (the acceptance
    contract riding the capture).  Key classes (tools/check_bench.py):
    ``*_gflops``/``*_gbps`` are rates the sentinel pages on,
    ``*_comm_bytes`` and ``*_overlap_frac`` are accounting — the
    payload bytes are pinned UNCHANGED vs the base engine by
    tests/test_comm.py, and the overlap fraction is the cost model's
    projected probe-hiding headroom, context not a rate."""
    import subprocess
    import sys

    from __graft_entry__ import _REPO, _cpu_env

    child = (
        "import jax, json\n"
        "import numpy as np\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from tpu_jordan.linalg import solve_system\n"
        "from tpu_jordan.obs import hwcost as _hwcost\n"
        "from tpu_jordan.ops import generate\n"
        "from tpu_jordan.tuning.measure import measure_direct\n"
        "import jax.numpy as jnp\n"
        "n, m, k, p = 4096, 128, 8, 8\n"
        "a = generate('rand', (n, n), jnp.float32)\n"
        "b = generate('rand', (n, k), jnp.float32, row_offset=n)\n"
        "r = solve_system(a, b, block_size=m, workers=p,\n"
        "                 engine='solve_lookahead')\n"
        "assert r.engine == 'solve_lookahead', r.engine\n"
        "base = solve_system(a, b, block_size=m, workers=p,\n"
        "                    engine='solve_sharded')\n"
        "assert np.array_equal(np.asarray(r.x), np.asarray(base.x)), \\\n"
        "    'probe-ahead X diverged bitwise from solve_sharded'\n"
        "from tpu_jordan.linalg.api import solve_mesh_backend\n"
        "mesh, lay, sc_a, sc_b, compile_fn, _ = "
        "solve_mesh_backend(p, n, m)\n"
        "W = sc_a(a, lay, mesh); X = sc_b(b, lay, mesh)\n"
        "run = compile_fn(W, X, mesh, lay, lookahead=True)\n"
        "meas = measure_direct(\n"
        "    lambda: jax.block_until_ready(run(W, X)[0]),\n"
        "    samples=3, warmup=1)\n"
        "flops = _hwcost.baseline_workload_flops(n, 'solve', k=k)\n"
        "from tpu_jordan.tuning.registry import (TunePoint,\n"
        "                                        probe_overlap_headroom)\n"
        "pt = TunePoint.create(n, m, jnp.float32, p, True,\n"
        "                      workload='solve')\n"
        "d = r.comm.drift or {}\n"
        "print(json.dumps({'n': n, 'm': m, 'k': k, 'mesh': f'p{p}',\n"
        "    'engine': r.engine,\n"
        "    'bitmatch_vs_solve_sharded': True,\n"
        "    'elapsed_s': round(meas.seconds, 3),\n"
        "    'gflops': round(flops / meas.seconds / 1e9, 1),\n"
        "    'spread_pct': meas.spread_pct,\n"
        "    'variance_flag': meas.variance_flag,\n"
        "    'rel_backward_error': r.rel_residual,\n"
        "    'overlap_frac': float(\n"
        "        f'{probe_overlap_headroom(pt):.4g}'),\n"
        "    'comm_payload_bytes': int(sum(\n"
        "        s.payload_bytes * s.executed for s in r.comm.sigs\n"
        "        if s.section == 'engine')),\n"
        "    'comm_gbps': d.get('achieved_gbps'),\n"
        "    'comm_vs_projected': d.get('comm_vs_projected')}))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", child], env=_cpu_env(8), cwd=_REPO,
            capture_output=True, text=True, timeout=900, check=True)
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        row["note"] = ("cpu-mesh probe-ahead solve leg, not chip "
                       "throughput; flops convention n^3*(1+k/n)")
        extra["solve_lookahead_sharded_4096"] = row
        extra["solve_lookahead_sharded_4096_k8_gflops"] = row["gflops"]
        extra["solve_lookahead_sharded_4096_k8_spread_pct"] = row[
            "spread_pct"]
        if row.get("variance_flag"):
            extra["solve_lookahead_sharded_4096_k8_variance_flag"] = \
                row["variance_flag"]
        # Sentinel classes: bytes + overlap_frac = accounting, GB/s =
        # rate (pages on quiet shortfalls).
        extra["solve_lookahead_sharded_4096_comm_bytes"] = row[
            "comm_payload_bytes"]
        extra["solve_lookahead_sharded_4096_overlap_frac"] = row[
            "overlap_frac"]
        if row.get("comm_gbps") is not None:
            extra["solve_lookahead_sharded_4096_comm_gbps"] = round(
                row["comm_gbps"], 4)
    except Exception as e:                      # noqa: BLE001
        extra["solve_lookahead_sharded_4096_error"] = str(e)[:200]


def _solve_fori_row(extra):
    """ISSUE 15 capture row ``solve_fori_8192``: the fori-compiled
    single-device solve engine at n=8192, m=64 — Nr=128, a point the
    UNROLLED solve engine refuses (MAX_UNROLL_NR=64): the row is the
    evidence that the cap is really lifted, captured with the standard
    robust fields.  GFLOP/s stays on the n³(1+k/n) useful-work
    convention; the executable's own ``cost_analysis`` FLOPs sit next
    to it (the fori engine's full-width updates pay ~2n³ —
    ``xla_vs_convention`` shows that honestly, like every accounting
    field)."""
    import jax
    import jax.numpy as jnp

    from tpu_jordan.linalg.engine import block_jordan_solve_fori
    from tpu_jordan.obs import hwcost as _hwcost
    from tpu_jordan.ops import generate
    from tpu_jordan.tuning.measure import measure_direct

    n, m, k = 8192, 64, 8
    try:
        a = generate("rand", (n, n), jnp.float32)
        b = generate("rand", (n, k), jnp.float32, row_offset=n)
        compiled = jax.jit(
            lambda aa, bb: block_jordan_solve_fori(aa, bb, block_size=m)
        ).lower(a, b).compile()
        cost = _hwcost.executable_cost(compiled)
        x, sing = compiled(a, b)
        jax.block_until_ready(x)
        if bool(sing):
            raise _Singular("solve_fori_8192: fixture flagged singular")

        def call(_c=compiled, _a=a, _b=b):
            jax.block_until_ready(_c(_a, _b)[0])

        meas = _retry_transient(
            lambda: measure_direct(call, samples=3, warmup=1))
        flops = _hwcost.baseline_workload_flops(n, "solve", k=k)
        extra["solve_fori_8192_k8_gflops"] = round(
            flops / meas.seconds / 1e9, 1)
        extra["solve_fori_8192_k8_spread_pct"] = meas.spread_pct
        if meas.variance_flag:
            extra["solve_fori_8192_k8_variance_flag"] = \
                meas.variance_flag
        extra["solve_fori_8192_flops_convention"] = "n^3*(1+k/n)"
        extra["solve_fori_8192_nr"] = -(-n // m)
        if cost.available and cost.flops:
            extra["solve_fori_8192_xla_flops"] = cost.flops
            extra["solve_fori_8192_xla_vs_convention"] = round(
                cost.flops / flops, 2)
    except Exception as e:                      # noqa: BLE001
        extra["solve_fori_8192_error"] = str(e)[:200]


def _ckpt_overhead_row(extra, n=4096, m=128, cadence=8):
    """ISSUE 20 capture row ``ckpt_overhead_4096``: the superstep
    checkpoint tax.  The fori invert engine at the headline size runs
    twice through tpu_jordan.resilience.checkpoint — once as a single
    monolithic segment (cadence = Nr: zero checkpoint writes) and once
    at cadence 8 (a host round-trip, a sha256 content checksum and an
    atomic write at every superstep boundary) — both WARM, so the
    delta is pure checkpoint tax.  The checkpointed GFLOP/s and the
    overhead pct are measured; ``*_bytes`` (snapshot size) and
    ``*_cadence`` (the interval knob that bought the durability) are
    accounting class (tools/check_bench.py ACCOUNTING_SUFFIXES): a
    dtype or cadence retune re-prices the same sweep and must never
    page — the overhead RATE still does."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from tpu_jordan.obs import hwcost as _hwcost
    from tpu_jordan.ops import generate
    from tpu_jordan.resilience.checkpoint import (CheckpointStore,
                                                  checkpointed_invert)
    from tpu_jordan.tuning.measure import measure_direct

    tmp = tempfile.mkdtemp(prefix="tpu_jordan_bench_ckpt_")
    try:
        store = CheckpointStore(tmp)
        a = generate("rand", (n, n), jnp.float32)
        nr = -(-n // m)

        def run(cad, rid):
            inv, sing, info = checkpointed_invert(
                a, m, store=store, run_id=rid, cadence=cad,
                engine="fori")
            jax.block_until_ready(inv)
            if bool(sing):
                raise _Singular("ckpt_overhead_4096: fixture singular")
            return info

        run(nr, "bench:mono:warm")   # compile the monolithic segment
        info = run(cadence, "bench:ckpt:warm")   # ...and the cadenced
        mono = _retry_transient(lambda: measure_direct(
            lambda: run(nr, "bench:mono"), samples=3, warmup=1))
        ckpt = _retry_transient(lambda: measure_direct(
            lambda: run(cadence, "bench:ckpt"), samples=3, warmup=1))
        flops = _hwcost.baseline_workload_flops(n, "invert")
        extra["ckpt_overhead_4096_gflops"] = round(
            flops / ckpt.seconds / 1e9, 1)
        extra["ckpt_overhead_4096_spread_pct"] = ckpt.spread_pct
        if ckpt.variance_flag:
            extra["ckpt_overhead_4096_variance_flag"] = \
                ckpt.variance_flag
        extra["ckpt_overhead_4096_pct"] = round(
            (ckpt.seconds - mono.seconds) / mono.seconds * 100.0, 1)
        extra["ckpt_overhead_4096_bytes"] = int(
            info["ckpt_bytes_last"])
        extra["ckpt_overhead_4096_cadence"] = cadence
        extra["ckpt_overhead_4096_writes_per_run"] = int(
            info["ckpt_written"])
    except Exception as e:                      # noqa: BLE001
        extra["ckpt_overhead_4096_error"] = str(e)[:200]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


#: BENCH_r04.json's 4096² number of record — the high-water mark the
#: r04→r05 dip fell from (diagnosed as single-sample session-lottery
#: noise, BASELINE.md "The r04→r05 4096² dip"); the dip guard row
#: compares every capture round against it WITH variance context so the
#: regression class can't recur silently.
R04_4096_GFLOPS = 11782.6


def _pallas_rows(extra, baseline_gflops, dip_only=False):
    """ISSUE 6 capture rows: the fused-Pallas-update grouped engine
    (ops/pallas_update.py) at the 4096² headline config and — full runs
    only — the 8192² grouped config plus its bf16-compute variant, with
    the bf16-vs-fp32 speedup recorded when both land.  Best-effort like
    every scale row: a failure records an error key, never loses the
    plain rows.  Returns {label: (gflops, acc)} for the rows that
    landed."""
    rows = [
        ("4096_m128_grouped_pallas", 4096, 128,
         dict(group=2, pallas=True), (8, 24)),
    ]
    if not dip_only:
        rows += [
            ("8192_m128_grouped_pallas", 8192, 128,
             dict(group=2, pallas=True), (3, 9)),
            ("8192_m128_grouped_pallas_bf16", 8192, 128,
             dict(group=2, pallas=True, mode="bf16"), (3, 9)),
        ]
    out = {}
    for label, n, m, kw, (r1, r2) in rows:
        try:
            gf, acc = _retry_transient(
                lambda: _measure(n, m, r1=r1, r2=r2, generator="rand",
                                 max_rel=None, refine=1, **kw))
        except Exception as ge:                 # noqa: BLE001
            extra[f"invert_{label}_error"] = str(ge)[:200]
            continue
        extra[f"invert_{label}_rand_gflops"] = round(gf, 1)
        extra[f"invert_{label}_vs_baseline"] = round(
            gf / baseline_gflops, 1)
        extra[f"invert_{label}_rel_residual"] = acc["rel_residual"]
        extra[f"invert_{label}_kappa"] = acc["kappa"]
        _record_spread(extra, f"invert_{label}", acc)
        out[label] = (gf, acc)
    f32 = out.get("8192_m128_grouped_pallas")
    b16 = out.get("8192_m128_grouped_pallas_bf16")
    if f32 and b16:
        # The ISSUE 6 acceptance comparison: bf16 steady-state vs its
        # fp32 twin at 8192² (>1 = bf16 faster).  Recorded even when
        # < 1 — on v5e fp32-HIGHEST is already bf16 passes (BASELINE.md
        # re-scope), so an honest negative here is a finding, not noise.
        extra["bf16_vs_fp32_speedup_8192"] = round(
            f32[1]["steady_state_s"] / b16[1]["steady_state_s"], 3)
    return out


def _workload_rows(extra):
    """The solve-workload capture rows (ISSUE 11 satellite):
    ``solve_4096`` (pivoting Gauss–Jordan on [A | B], k=8 RHS),
    ``spd_4096`` (the pivot-free assume="spd" path on the KMS SPD
    fixture), and ``complex64_2048`` — each with the standard robust
    capture (median-of-3, spread %, variance flag), the executable's
    own ``cost_analysis`` accounting, and a backward-error residual
    gate.  GFLOP/s uses the workload-aware n³(1+k/n) convention
    (``obs/hwcost.baseline_workload_flops``) — NOT 2n³, which would
    silently inflate a solve headline ~2x against the wrong
    denominator.  Best-effort: a failing row records an error key and
    never loses the invert rows."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_jordan.linalg.engine import block_jordan_solve
    from tpu_jordan.obs import hwcost as _hwcost
    from tpu_jordan.ops import generate
    from tpu_jordan.resilience.degrade import solve_gate_threshold
    from tpu_jordan.resilience.policy import ResiliencePolicy
    from tpu_jordan.tuning.measure import measure_direct

    rows = (
        ("solve_4096", 4096, 128, 8, "rand", False, jnp.float32),
        ("spd_4096", 4096, 128, 8, "kms", True, jnp.float32),
        ("complex64_2048", 2048, 128, 8, "crand", False, jnp.complex64),
    )
    gate_policy = ResiliencePolicy()
    for label, n, m, k, gen, spd, dtype in rows:
        try:
            a = generate(gen, (n, n), dtype)
            b = generate("crand" if jnp.dtype(dtype).kind == "c"
                         else "rand", (n, k), dtype, row_offset=n)
            compiled = jax.jit(
                lambda aa, bb, _m=m, _spd=spd: block_jordan_solve(
                    aa, bb, block_size=_m, spd=_spd)
            ).lower(a, b).compile()
            cost = _hwcost.executable_cost(compiled)
            x, sing = compiled(a, b)
            jax.block_until_ready(x)
            if bool(sing):
                raise _Singular(f"{label}: fixture flagged singular")
            # Backward-error gate (the solve workloads' residual
            # semantics — resilience/degrade.solve_gate_threshold).
            r = np.asarray(jnp.matmul(a, x) - b)
            na = float(jnp.max(jnp.sum(jnp.abs(a), axis=-1)))
            nx = float(jnp.max(jnp.sum(jnp.abs(x), axis=-1)))
            nb = float(jnp.max(jnp.sum(jnp.abs(b), axis=-1)))
            rel = float(np.abs(r).sum(axis=-1).max()) / (na * nx + nb)
            thr = solve_gate_threshold(gate_policy, n, dtype)
            if not rel <= thr:       # raised, not asserted (-O safe)
                raise _Singular(
                    f"{label}: backward error {rel:.2e} > gate "
                    f"{thr:.2e}")

            def call(_c=compiled, _a=a, _b=b):
                jax.block_until_ready(_c(_a, _b)[0])

            meas = _retry_transient(
                lambda: measure_direct(call, samples=3, warmup=1))
            flops = _hwcost.baseline_workload_flops(n, "solve", k=k)
            gfs = sorted(flops / s / 1e9 for s in meas.accepted)
            extra[f"{label}_k{k}_gflops"] = round(flops / meas.seconds
                                                  / 1e9, 1)
            extra[f"{label}_k{k}_gflops_minmax"] = [round(gfs[0], 1),
                                                    round(gfs[-1], 1)]
            extra[f"{label}_k{k}_spread_pct"] = meas.spread_pct
            if meas.variance_flag:
                extra[f"{label}_k{k}_variance_flag"] = meas.variance_flag
            extra[f"{label}_rel_backward_error"] = rel
            extra[f"{label}_flops_convention"] = "n^3*(1+k/n)"
            if cost.available and cost.flops:
                extra[f"{label}_xla_flops"] = cost.flops
                if meas.seconds > 0:
                    extra[f"{label}_xla_gflops"] = round(
                        cost.flops / meas.seconds / 1e9, 1)
                extra[f"{label}_xla_vs_analytic"] = round(
                    cost.flops / flops, 3)
        except Exception as ge:                      # noqa: BLE001
            extra[f"{label}_error"] = str(ge)[:200]


def _update_rows(extra, n=4096, m=128, k=32, amortized_updates=8):
    """The resident-update capture rows (ISSUE 12 satellite):

      * ``update_4096_k32`` — the serve-shaped SMW update executable
        (mutate A, refresh the inverse, re-verify against the mutated
        matrix — one launch, ``linalg.update.smw_update_with_metrics``)
        under the standard robust capture; GFLOP/s uses the 4n²k+2nk²
        update convention (``obs/hwcost.baseline_workload_flops``) —
        the deliberate in-launch O(n³) verification shows up in the
        ``xla_flops`` key next to it, never inside the headline
        denominator.
      * ``update_resident_amortized`` — what a resident handle buys a
        re-factorizing caller (the MPAX LP/QP shape): M mutations
        served as 1 fresh invert + M rank-k updates, rated in the 2n³
        invert convention each request REPRESENTS, vs M fresh inverts
        (``update_resident_speedup_x``).  Spread is the worse of the
        two component captures (documented — the row is a composition).

    Best-effort: a failing row records an error key and never loses
    the invert rows."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_jordan.linalg.update import smw_update_with_metrics
    from tpu_jordan.obs import hwcost as _hwcost
    from tpu_jordan.ops import generate
    from tpu_jordan.ops.jordan_inplace import block_jordan_invert_inplace
    from tpu_jordan.resilience.degrade import gate_threshold
    from tpu_jordan.resilience.policy import ResiliencePolicy
    from tpu_jordan.tuning.measure import measure_direct

    label = f"update_{n}_k{k}"
    try:
        a = generate("rand", (n, n), jnp.float32)
        rng = np.random.default_rng(12)
        scale = 1.0 / np.sqrt(float(n) * k)
        u = jnp.asarray(rng.standard_normal((n, k)).astype(np.float32)
                        * scale)
        v = jnp.asarray(rng.standard_normal((n, k)).astype(np.float32)
                        * scale)
        inv_compiled = jax.jit(
            lambda aa: block_jordan_invert_inplace(aa, block_size=m)
        ).lower(a).compile()
        inv0, sing0 = inv_compiled(a)
        jax.block_until_ready(inv0)
        if bool(sing0):
            raise _Singular(f"{label}: fixture flagged singular")
        upd_compiled = jax.jit(
            lambda aa, ii, uu, vv: smw_update_with_metrics(aa, ii, uu,
                                                           vv)
        ).lower(a, inv0, u, v).compile()
        cost = _hwcost.executable_cost(upd_compiled)
        out = upd_compiled(a, inv0, u, v)
        jax.block_until_ready(out[1])
        _, _, sing1, kappa1, rel1 = out
        if bool(sing1):
            raise _Singular(f"{label}: update flagged singular")
        rel1, kappa1 = float(rel1), float(kappa1)
        thr = gate_threshold(ResiliencePolicy(), n, kappa1, jnp.float32)
        if not rel1 <= thr:          # raised, not asserted: the gate
            raise _Singular(         # must survive python -O
                f"{label}: updated-inverse residual {rel1:.2e} > gate "
                f"{thr:.2e}")

        def call_upd(_c=upd_compiled, _a=a, _i=inv0, _u=u, _v=v):
            jax.block_until_ready(_c(_a, _i, _u, _v)[1])

        def call_inv(_c=inv_compiled, _a=a):
            jax.block_until_ready(_c(_a)[0])

        meas_u = _retry_transient(
            lambda: measure_direct(call_upd, samples=3, warmup=1))
        meas_i = _retry_transient(
            lambda: measure_direct(call_inv, samples=3, warmup=1))
        flops = _hwcost.baseline_workload_flops(n, "update", k=k)
        gfs = sorted(flops / s / 1e9 for s in meas_u.accepted)
        extra[f"{label}_gflops"] = round(flops / meas_u.seconds / 1e9, 1)
        extra[f"{label}_gflops_minmax"] = [round(gfs[0], 1),
                                           round(gfs[-1], 1)]
        extra[f"{label}_spread_pct"] = meas_u.spread_pct
        if meas_u.variance_flag:
            extra[f"{label}_variance_flag"] = meas_u.variance_flag
        extra[f"{label}_rel_residual"] = rel1
        extra[f"{label}_flops_convention"] = "4n^2k + 2nk^2"
        extra[f"{label}_update_seconds"] = round(meas_u.seconds, 6)
        extra[f"{label}_fresh_invert_seconds"] = round(meas_i.seconds, 6)
        if cost.available and cost.flops:
            extra[f"{label}_xla_flops"] = cost.flops
            if meas_u.seconds > 0:
                extra[f"{label}_xla_gflops"] = round(
                    cost.flops / meas_u.seconds / 1e9, 1)
            extra[f"{label}_xla_vs_analytic"] = round(cost.flops / flops,
                                                      3)
        # Capacity accounting fields (ISSUE 13 satellite): the update
        # executable's memory_analysis HBM footprint next to the
        # 2n²·dtype a resident handle pins — both accounting-class,
        # excluded from cross-round perf comparison by check_bench.
        if cost.available and cost.hbm_bytes is not None:
            extra[f"{label}_peak_hbm_bytes"] = cost.hbm_bytes
        from tpu_jordan.serve.handles import resident_handle_bytes

        extra[f"{label}_resident_handle_bytes"] = resident_handle_bytes(
            n, jnp.float32)

        # ---- the amortized resident-handle row ----------------------
        M = amortized_updates
        t_resident = meas_i.seconds + M * meas_u.seconds
        t_scratch = M * meas_i.seconds
        inv_flops = _hwcost.baseline_invert_flops(n)
        extra["update_resident_amortized_gflops"] = round(
            M * inv_flops / t_resident / 1e9, 1)
        extra["update_resident_amortized_updates"] = M
        extra["update_resident_amortized_spread_pct"] = max(
            meas_u.spread_pct or 0.0, meas_i.spread_pct or 0.0)
        vflag = meas_u.variance_flag or meas_i.variance_flag
        if vflag:
            extra["update_resident_amortized_variance_flag"] = vflag
        extra["update_resident_speedup_x"] = round(
            t_scratch / t_resident, 2)
        extra["update_resident_convention"] = (
            "M mutations as 1 fresh invert + M rank-k SMW updates, "
            "rated at 2n^3 per served inverse")
    except Exception as ge:                          # noqa: BLE001
        extra[f"{label}_error"] = str(ge)[:200]


def _lp_demo_row(extra, n=16, timeout=900):
    """ISSUE 17 capture row ``lp_demo_iters``: the LP/QP optimization
    driver's sustained correlated traffic (four seeded driver runs —
    LP well/ill revised simplex, QP well/ill primal active-set — each
    one ``invert(resident=True)`` plus a rank-k ``update`` +
    verification ``solve`` stream) through a warmed 2-replica fleet in
    an x64 subprocess.  The recorded numbers are workload-shape
    context, deliberately NOT rate-class: iteration counts, the
    update ledger totals, wall seconds, and iters/s are
    fleet-overhead-dominated at this tiny n — none end in a
    ``*_gflops``/``*_gbps`` suffix, so tools/check_bench.py never
    pages on them (trap-pinned in tests/test_bench_check.py).  The
    driver's PERF contract lives in the ``update_batched_amortized``
    row next door.  Best-effort like every non-contract row."""
    import subprocess
    import sys

    from __graft_entry__ import _REPO, _cpu_env

    child = (
        "import jax, json, time\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.config.update('jax_enable_x64', True)\n"
        "import jax.numpy as jnp\n"
        "from tpu_jordan.fleet import JordanFleet\n"
        "from tpu_jordan.lpqp import (lp_instance, qp_instance,\n"
        "                             solve_lp, solve_qp)\n"
        "from tpu_jordan.obs.metrics import REGISTRY\n"
        f"n = {n}\n"
        "probs = [\n"
        "    ('lp_well', solve_lp, lp_instance(m=n, cond='well')),\n"
        "    ('lp_ill', solve_lp, lp_instance(m=n, cond='ill')),\n"
        "    ('qp_well', solve_qp, qp_instance(n=n, cond='well')),\n"
        "    ('qp_ill', solve_qp, qp_instance(n=n, cond='ill'))]\n"
        "with JordanFleet(replicas=2, engine='auto',\n"
        "                 dtype=jnp.float64, batch_cap=1,\n"
        "                 max_wait_ms=0.5, stable_after_s=0.2,\n"
        "                 liveness_deadline_s=5.0) as fleet:\n"
        "    fleet.warmup([n], update_shapes=[(n, 1), (n, 2)],\n"
        "                 solve_shapes=[(n, 1)])\n"
        "    c0 = REGISTRY.counter('tpu_jordan_compiles_total').total()\n"
        "    legs = {}\n"
        "    t0 = time.perf_counter()\n"
        "    for name, solver, prob in probs:\n"
        "        rep = solver(prob, fleet)\n"
        "        legs[name] = {'iters': rep.iterations,\n"
        "                      'updates': rep.updates,\n"
        "                      'solves': rep.solves,\n"
        "                      'converged': bool(rep.converged),\n"
        "                      'kkt_rel': rep.kkt_rel_final}\n"
        "    secs = time.perf_counter() - t0\n"
        "    dc = (REGISTRY.counter('tpu_jordan_compiles_total')\n"
        "          .total() - c0)\n"
        "print(json.dumps({'n': n, 'legs': legs,\n"
        "                  'seconds': round(secs, 3),\n"
        "                  'compiles_after_warmup': int(dc)}))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", child], env=_cpu_env(2), cwd=_REPO,
            capture_output=True, text=True, timeout=timeout, check=True)
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        legs = row["legs"]
        if not all(leg["converged"] for leg in legs.values()):
            raise RuntimeError(
                "driver leg(s) did not converge: " + ", ".join(
                    name for name, leg in legs.items()
                    if not leg["converged"]))
        iters = sum(leg["iters"] for leg in legs.values())
        extra["lp_demo_iters"] = iters
        extra["lp_demo_iters_by_leg"] = {name: leg["iters"]
                                         for name, leg in legs.items()}
        extra["lp_demo_updates"] = sum(leg["updates"]
                                       for leg in legs.values())
        extra["lp_demo_solves"] = sum(leg["solves"]
                                      for leg in legs.values())
        extra["lp_demo_seconds"] = row["seconds"]
        if row["seconds"] > 0:
            extra["lp_demo_iters_per_s"] = round(iters / row["seconds"],
                                                 2)
        extra["lp_demo_compiles_after_warmup"] = row[
            "compiles_after_warmup"]
    except Exception as e:                      # noqa: BLE001
        extra["lp_demo_iters_error"] = str(e)[:200]


def _update_batched_row(extra, n=64, cap=4, rounds=5, timeout=900):
    """ISSUE 17 capture row ``update_batched_amortized``: the batched
    SMW update lane's warm amortization — ``cap`` distinct resident
    handles stream rank-1 updates through a warmed
    :class:`~tpu_jordan.serve.service.JordanService`, first strictly
    sequentially (the one-per-launch baseline, occupancy 1), then
    submitted together so the batcher fuses them into one vmapped
    launch.  Per-update amortized cost = launch ``execute_seconds`` /
    measured ``batch_occupancy``; the headline
    ``update_batched_amortized_gflops`` rates it in the 4n²k+2nk²
    update convention (a RATE key the sentinel pages on, with spread
    across the per-round medians), and ``update_batched_speedup_x``
    records the amortization factor EVEN WHEN < 1 — a regressed lane
    must be visible, never silently dropped.  Zero compiles across the
    warm measurement is the pin.  Best-effort like every non-contract
    row."""
    import subprocess
    import sys

    from __graft_entry__ import _REPO, _cpu_env

    child = (
        "import jax, json\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from tpu_jordan.obs.metrics import REGISTRY\n"
        "from tpu_jordan.serve.service import JordanService\n"
        f"n, cap, rounds = {n}, {cap}, {rounds}\n"
        "rng = np.random.default_rng(17)\n"
        "scale = 1.0 / np.sqrt(float(n))\n"
        "def med(s):\n"
        "    s = sorted(s)\n"
        "    return s[len(s) // 2]\n"
        "seq_r, bat_r, occs = [], [], []\n"
        "with JordanService(engine='auto', dtype=jnp.float32,\n"
        "                   batch_cap=cap, max_wait_ms=25.0) as svc:\n"
        "    svc.warmup(update_shapes=[(n, 1)])\n"
        "    refs = [svc.invert((rng.standard_normal((n, n))\n"
        "                        + n * np.eye(n)).astype(np.float32),\n"
        "                       resident=True,\n"
        "                       handle_id='amort-%d' % i, timeout=600)\n"
        "            for i in range(cap)]\n"
        "    muts = [(rng.standard_normal((n, 1)).astype(np.float32)\n"
        "             * scale,\n"
        "             rng.standard_normal((n, 1)).astype(np.float32)\n"
        "             * scale) for _ in range(cap)]\n"
        "    c0 = REGISTRY.counter('tpu_jordan_compiles_total').total()\n"
        "    for _ in range(rounds):\n"
        "        lat = []\n"
        "        for ref, (u, v) in zip(refs, muts):\n"
        "            lat.append(svc.update(ref, u, v,\n"
        "                                  timeout=600).execute_seconds)\n"
        "        seq_r.append(med(lat))\n"
        "        futs = [svc.submit_update(ref, u, v)\n"
        "                for ref, (u, v) in zip(refs, muts)]\n"
        "        res = [f.result(600) for f in futs]\n"
        "        occs.append(max(r.batch_occupancy for r in res))\n"
        "        bat_r.append(med([\n"
        "            r.execute_seconds / r.batch_occupancy\n"
        "            for r in res]))\n"
        "    dc = (REGISTRY.counter('tpu_jordan_compiles_total')\n"
        "          .total() - c0)\n"
        "seq_s, bat_s = med(seq_r), med(bat_r)\n"
        "print(json.dumps({'n': n, 'cap': cap, 'rounds': rounds,\n"
        "    'occupancy': int(max(occs)),\n"
        "    'one_per_launch_ms': round(seq_s * 1e3, 4),\n"
        "    'amortized_ms': round(bat_s * 1e3, 4),\n"
        "    'amortized_s': bat_s,\n"
        "    'speedup_x': round(seq_s / bat_s, 3),\n"
        "    'spread_pct': round(\n"
        "        100.0 * (max(bat_r) - min(bat_r)) / bat_s, 1),\n"
        "    'compiles_delta': int(dc)}))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", child], env=_cpu_env(8), cwd=_REPO,
            capture_output=True, text=True, timeout=timeout, check=True)
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        if row["occupancy"] <= 1:
            raise RuntimeError(
                f"batched lane never fused: occupancy "
                f"{row['occupancy']}")
        from tpu_jordan.obs import hwcost as _hwcost

        flops = _hwcost.baseline_workload_flops(n, "update", k=1)
        extra["update_batched_amortized_gflops"] = round(
            flops / row["amortized_s"] / 1e9, 4)
        extra["update_batched_amortized_spread_pct"] = row["spread_pct"]
        if row["spread_pct"] >= 10.0:
            extra["update_batched_amortized_variance_flag"] = (
                "high_spread")
        extra["update_batched_one_per_launch_ms"] = row[
            "one_per_launch_ms"]
        extra["update_batched_amortized_ms"] = row["amortized_ms"]
        extra["update_batched_speedup_x"] = row["speedup_x"]
        extra["update_batched_occupancy"] = row["occupancy"]
        extra["update_batched_flops_convention"] = "4n^2k + 2nk^2"
        extra["update_batched_compiles_delta"] = row["compiles_delta"]
    except Exception as e:                      # noqa: BLE001
        extra["update_batched_amortized_error"] = str(e)[:200]


def _serve_mesh_row(extra, n=4096, m=128, p=8, rounds=2, timeout=900):
    """ISSUE 18 capture row ``serve_mesh_4096``: the mesh-backed serve
    lane at the headline size — a request whose single-device
    projection exceeds the lane budget served through the warmed
    p-device lane on the forced 8-virtual-device CPU mesh (the
    __graft_entry__ dryrun recipe).  Context + accounting only, BY
    DESIGN: ``*_projected_lane_bytes`` (the per-device admission
    number) and ``*_measured_lane_bytes`` (the compiled lane's
    capacity-ledger footprint) end in ``_bytes`` — the accounting
    class ``tools/check_bench.py`` never compares across rounds (a
    compiler or layout change re-prices the same lane); occupancy (1
    by the mesh-lane contract), execute wall time, and the
    zero-compile warm-path delta are plain context keys.  No new rate
    key: CPU-mesh serve wall time is not chip throughput.  Best-effort
    like every non-contract row."""
    import subprocess
    import sys

    from __graft_entry__ import _REPO, _cpu_env

    child = (
        "import jax, json\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from tpu_jordan.obs import capacity as cap\n"
        "from tpu_jordan.obs.metrics import REGISTRY\n"
        "from tpu_jordan.serve.executors import projected_lane_bytes\n"
        "from tpu_jordan.serve.service import JordanService\n"
        f"n, m, p, rounds = {n}, {m}, {p}, {rounds}\n"
        "single = projected_lane_bytes(n, 1, jnp.float32)\n"
        "per_dev = projected_lane_bytes(n, 1, jnp.float32, devices=p)\n"
        "budget = (single + per_dev) // 2\n"
        "rng = np.random.default_rng(18)\n"
        "with JordanService(dtype=jnp.float32, batch_cap=1,\n"
        "                   max_wait_ms=1.0, block_size=m,\n"
        "                   mesh_shapes=(p,),\n"
        "                   lane_budget_bytes=budget) as svc:\n"
        "    svc.warmup(mesh_shapes=[(n, p)])\n"
        "    measured = cap.live_bytes('executor_lanes')\n"
        "    c0 = REGISTRY.counter('tpu_jordan_compiles_total').total()\n"
        "    times, occs = [], []\n"
        "    for _ in range(rounds):\n"
        "        a = rng.standard_normal((n, n)).astype(np.float32)\n"
        "        r = svc.submit(a).result(timeout=600)\n"
        "        assert not r.singular and r.rel_residual < 1e-2\n"
        "        times.append(r.execute_seconds)\n"
        "        occs.append(r.batch_occupancy)\n"
        "    dc = (REGISTRY.counter('tpu_jordan_compiles_total')\n"
        "          .total() - c0)\n"
        "times.sort()\n"
        "print(json.dumps({'n': n, 'm': m, 'mesh': 'p%d' % p,\n"
        "    'projected_lane_bytes': int(per_dev),\n"
        "    'single_device_bytes': int(single),\n"
        "    'lane_budget_bytes': int(budget),\n"
        "    'measured_lane_bytes': int(measured),\n"
        "    'occupancy': int(max(occs)),\n"
        "    'execute_ms': round(times[len(times) // 2] * 1e3, 2),\n"
        "    'compiles_delta': int(dc)}))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", child], env=_cpu_env(8), cwd=_REPO,
            capture_output=True, text=True, timeout=timeout, check=True)
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        if row["compiles_delta"] != 0:
            raise RuntimeError(
                f"{row['compiles_delta']} compile(s) on the warm "
                f"mesh-serve path")
        if row["occupancy"] != 1:
            raise RuntimeError(
                f"mesh lane dispatched at occupancy "
                f"{row['occupancy']}, contract is 1")
        row["note"] = ("cpu-mesh serve-lane context leg, not chip "
                       "throughput")
        extra["serve_mesh_4096"] = row
        # Top-level sentinel keys: both byte fields are accounting-
        # class (tools/check_bench.py never rate-compares *_bytes).
        extra["serve_mesh_4096_projected_lane_bytes"] = row[
            "projected_lane_bytes"]
        extra["serve_mesh_4096_measured_lane_bytes"] = row[
            "measured_lane_bytes"]
        extra["serve_mesh_4096_occupancy"] = row["occupancy"]
        extra["serve_mesh_4096_execute_ms"] = row["execute_ms"]
        extra["serve_mesh_4096_compiles_delta"] = row["compiles_delta"]
    except Exception as e:                      # noqa: BLE001
        extra["serve_mesh_4096_error"] = str(e)[:200]


def _dip_guard(extra, candidates):
    """The r04→r05 4096² regression guard (ISSUE 6 satellite; `make
    bench-dip` reproduces just this row).  The best 4096² capture of
    the round — plain engine or fused-Pallas engine — is compared to
    the r04 reference; ``regressed`` is True only when the shortfall
    exceeds 10% AND the session's own measured spread cannot explain it
    (the diagnosed root cause of the original dip was exactly a
    single-sample capture in a high-variance session, so a guard
    without variance context would re-flag every noisy session instead
    of real regressions)."""
    cands = {k: v for k, v in candidates.items() if v is not None}
    if not cands:
        extra["dip_guard_4096"] = {"error": "no 4096 capture landed"}
        return
    best_label, (best_gf, best_acc) = max(cands.items(),
                                          key=lambda kv: kv[1][0])
    spread = float(best_acc.get("spread_pct") or 0.0)
    extra["dip_guard_4096"] = {
        "r04_reference_gflops": R04_4096_GFLOPS,
        "best_gflops": round(best_gf, 1),
        "best_config": best_label,
        "delta_pct": round(100.0 * (best_gf / R04_4096_GFLOPS - 1.0), 1),
        "spread_pct": spread,
        "regressed": bool(best_gf < 0.9 * R04_4096_GFLOPS
                          and spread < 10.0),
    }


def main(argv=None):
    import sys

    argv = sys.argv[1:] if argv is None else argv
    dip_only = "--dip-guard" in argv
    baseline_gflops = 6.8  # BASELINE.md: reference fp64, m=48, 1 CPU core

    # Environment fingerprint FIRST (ISSUE 10 satellite): jax/jaxlib
    # versions, device kind, host cores — what makes cross-round BENCH
    # comparisons (and the tools/check_bench.py sentinel's variance
    # judgments) interpretable.  The sentinel treats missing env in old
    # rounds as unknown, never as regressed.
    from tpu_jordan.obs.hwcost import runtime_env

    gf_4096, acc_4096 = _retry_transient(
        lambda: _measure(4096, 128, r1=8, r2=24))
    extra = {
        "env": runtime_env(),
        "rel_residual_4096": acc_4096["rel_residual"],
        "kappa_4096": acc_4096["kappa"],
    }
    _record_spread(extra, "invert_4096", acc_4096)

    # Fused-Pallas rows (ISSUE 6) + the 4096² dip guard over the best
    # capture of the round.
    pallas = _pallas_rows(extra, baseline_gflops, dip_only=dip_only)
    cands = {"m128_plain": (gf_4096, acc_4096)}
    if "4096_m128_grouped_pallas" in pallas:
        cands["m128_grouped_pallas"] = pallas["4096_m128_grouped_pallas"]
    _dip_guard(extra, cands)

    if dip_only:
        print(json.dumps({
            "metric": "invert_4096x4096_f32_gflops",
            "value": round(gf_4096, 1),
            "unit": "GFLOP/s",
            "vs_baseline": round(gf_4096 / baseline_gflops, 1),
            "extra": extra,
        }))
        return

    # 8192 row: m=256 (round-4 tuned), m=384 knife-edge fallback.
    m_8192 = 256
    try:
        gf_8192, acc_8192 = _retry_transient(
            lambda: _measure(8192, m_8192, r1=3, r2=9))
    except _Singular:
        m_8192 = 384
        gf_8192, acc_8192 = _retry_transient(
            lambda: _measure(8192, m_8192, r1=3, r2=9))
    extra.update({
        f"invert_8192x8192_f32_m{m_8192}_gflops": round(gf_8192, 1),
        "vs_baseline_8192": round(gf_8192 / baseline_gflops, 1),
        "rel_residual_8192": acc_8192["rel_residual"],
        "kappa_8192": acc_8192["kappa"],
    })
    _record_spread(extra, "invert_8192", acc_8192)
    # 8192 scale row, best-effort (VERDICT r4 weak #3: the 8192-class
    # captured number must reflect the best engine, not the |i−j|
    # contract row): rand fixture, delayed-group-update engine at
    # m=128/k=2 — measured 65.3 ms = 16.8 TF/s (55% of envelope) in the
    # round-5 session; same capture ladder as the 16384 row.
    tiers8 = [
        ("m128_grouped2", 128, dict(group=2)),
        ("m128_grouped2_fori", 128, dict(group=2, fori=True)),
    ]
    _, acc8 = _capture_ladder(extra, 8192, tiers8, r1=3, r2=9,
                                baseline_gflops=baseline_gflops,
                                vs_key="vs_baseline_8192_grouped")
    if acc8 is not None:
        extra["rel_residual_8192_grouped"] = acc8["rel_residual"]
        extra["kappa_8192_grouped"] = acc8["kappa"]
        _record_spread(extra, "invert_8192_grouped", acc8)

    # 16384 scale point, best-effort (the two contract configs above must
    # never be lost to a failure here): |i−j| genuinely exceeds fp32 at
    # n=16384 (PHASES.md), so this row uses the deterministic
    # well-conditioned 'rand' fixture and gates at 3x the predicted
    # eps·n·κ∞ bound (VERDICT r3 #3) rather than a loose static rel.
    # Primary config: the delayed-group-update engine at m=128/k=2 —
    # measured 396 ms = 22.2 TF/s (72% of the matmul envelope) AND the
    # better residual (3.0e-3 vs 1.4e-2).  Capture ladder (VERDICT r4
    # weak #1: the best engine must be the number of record): each tier
    # retries once on the transient remote-compile failure class; tier 2
    # is the grouped-fori twin whose seconds-flat compile shrinks the
    # flake window ~40x; tier 3 is the plain engine at m=256.
    tiers16 = [
        ("m128_grouped2", 128, dict(group=2)),
        ("m128_grouped2_fori", 128, dict(group=2, fori=True)),
        ("m256_plain", 256, dict()),
    ]
    _, acc16 = _capture_ladder(extra, 16384, tiers16, r1=2, r2=5,
                                  baseline_gflops=baseline_gflops,
                                  vs_key="vs_baseline_16384")
    if acc16 is not None:
        # Robust-capture + cost keys in the shared PREFIX style
        # (invert_16384_spread_pct, ...) so tools/check_bench.py's
        # exact-stem variance lookup finds them (ISSUE 10: the suffix
        # style spread_pct_16384 was invisible to the sentinel);
        # accuracy keys keep the historical suffix names.
        _record_spread(extra, "invert_16384", acc16)
        _RECORDED = {"gflops_minmax", "spread_pct",
                     "iqr_rejected_samples", "variance_flag",
                     "first_call_compile_inclusive_s", "steady_state_s",
                     "xla_flops", "xla_gflops", "xla_vs_2n3",
                     "arithmetic_intensity"}
        for k, v in acc16.items():
            if k not in _RECORDED:
                extra[f"{k}_16384"] = v

    # Batched tiers (ISSUE 3 satellite / VERDICT r5 item 5): the
    # 512×512² dedicated-engine row and the largest-fitting B×2048²
    # tier, with per-element singular counts and element-0 residual
    # gates — the batch north star finally carried by the driver
    # capture.  Best-effort like the sharded row below.
    _batched_rows(extra, baseline_gflops)

    # Solve-workload tiers (ISSUE 11 satellite): solve_4096 (pivoting
    # [A | B]), spd_4096 (pivot-free fast path on the KMS SPD fixture),
    # complex64_2048 — best-effort like every non-contract row.
    _workload_rows(extra)

    # Resident-update tiers (ISSUE 12 satellite): the rank-32 SMW
    # update executable at 4096² plus the amortized resident-handle
    # row — best-effort like every non-contract row; the sentinel
    # (tools/check_bench.py) watches both *_gflops keys with their
    # spread stats from the round they first land.
    _update_rows(extra)

    # LP/QP driver tiers (ISSUE 17): the optimization-driver workload
    # context row (iteration/update/solve counts — never rate-compared)
    # and the batched-update-lane amortization row (the *_gflops rate
    # the sentinel pages on; speedup recorded even when < 1).
    # Best-effort like every non-contract row.
    _lp_demo_row(extra)
    _update_batched_row(extra)

    # Sharded-output tier: swapfree × gather=False (bucketed ppermute),
    # best-effort — a failure records an error key, never loses the
    # chip rows above.
    _sharded_swapfree_row(extra)

    # Distributed-solve tiers (ISSUE 15 satellite): the sharded [A | B]
    # elimination on the virtual 1D mesh (comm bytes + GB/s sentinel
    # fields) and the fori solve engine at Nr=128 — the point the
    # unrolled engine refuses.  Best-effort like every non-contract row.
    _solve_sharded_row(extra)
    _solve_fori_row(extra)

    # Probe-ahead tiers (ISSUE 16): the single-chip lookahead engine at
    # the headline size (parity expectation — on one chip the schedule
    # must cost nothing) and the distributed probe-ahead solve on the
    # virtual 1D mesh (bit-compared against solve_sharded in-row, with
    # the modeled overlap headroom as an accounting field).  Best-effort
    # like every non-contract row.
    _lookahead_row(extra)
    _solve_lookahead_sharded_row(extra)

    # Mesh-backed serve lane (ISSUE 18): the over-budget request served
    # through the warmed p8 lane at the headline size — projected vs
    # measured per-device lane bytes (accounting-class, never
    # rate-compared) with the zero-compile warm pin.  Best-effort like
    # every non-contract row.
    _serve_mesh_row(extra)

    # Checkpoint-overhead tier (ISSUE 20): the superstep checkpoint
    # tax at the headline size — warm monolithic vs warm cadence-8
    # checkpointed sweep through the same segmented machinery, with
    # the snapshot bytes and the cadence knob as accounting fields.
    # Best-effort like every non-contract row.
    _ckpt_overhead_row(extra)

    print(json.dumps({
        "metric": "invert_4096x4096_f32_gflops",
        "value": round(gf_4096, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gf_4096 / baseline_gflops, 1),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
