"""Per-phase cost breakdown of the single-chip blocked Jordan inversion.

Times each phase of a super-step in isolation (same shapes as the full
run) plus the full inversion.

Timing method (tunnel-safe): the op is repeated inside one jitted
``fori_loop`` with a *dynamic* trip count (one compile) and a real data
dependency between iterations; each measurement runs at two trip counts
and reports the slope (t(r2) - t(r1)) / (r2 - r1), so constant offsets —
tunnel RTT, dispatch, readback — cancel exactly.

Usage: python benchmarks/phase_bench.py [n] [m]
Writes a markdown table to stdout; numbers live in benchmarks/PHASES.md.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_jordan.utils.benchmarking import slope_time  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_jordan.ops import block_jordan_invert, generate
    from tpu_jordan.ops.block_inverse import batched_block_inverse
    from tpu_jordan.ops.pallas_block_inverse import (
        pallas_batched_block_inverse,
    )

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    m = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    Nr = n // m
    print(f"# n={n} m={m} Nr={Nr}")

    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.standard_normal((n, 2 * n)), jnp.float32)
    cands = jnp.asarray(rng.standard_normal((Nr, m, m)), jnp.float32)
    H = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)
    E = jnp.asarray(rng.standard_normal((n, m)), jnp.float32)
    prow = jnp.asarray(rng.standard_normal((m, 2 * n)), jnp.float32)

    rows = []

    def phase(name, fn, args):
        t = slope_time(fn, args)
        rows.append((name, t * 1e3, Nr * t))

    phase("probe pallas (Nr,m,m)",
          lambda c: pallas_batched_block_inverse(c)[0], (cands,))
    phase("probe XLA (Nr,m,m)",
          lambda c: batched_block_inverse(c, None, None)[0], (cands,))
    phase("eliminate HIGHEST",
          lambda W, E, p: W - jnp.matmul(
              E, p, precision=lax.Precision.HIGHEST), (W, E, prow))
    phase("eliminate HIGH",
          lambda W, E, p: W - jnp.matmul(
              E, p, precision=lax.Precision.HIGH), (W, E, prow))
    phase("eliminate DEFAULT",
          lambda W, E, p: W - jnp.matmul(
              E, p, precision=lax.Precision.DEFAULT), (W, E, prow))

    def slices(W):
        col = lax.dynamic_slice(W, (0, 37 * 8), (n, m))
        r1_ = lax.dynamic_slice(W, (5 * m, 0), (m, 2 * n))
        W = lax.dynamic_update_slice(W, r1_, (2 * m, 0))
        return W + 0 * jnp.sum(col)

    phase("slice/update traffic", slices, (W,))
    phase("normalize HIGHEST",
          lambda H, r: jnp.matmul(H, r, precision=lax.Precision.HIGHEST),
          (H, prow))

    a = generate("absdiff", (n, n), jnp.float32)

    def full(a):
        inv, _ = block_jordan_invert(a, block_size=m)
        return inv

    full_t = slope_time(full, (a,), r1=2, r2=6)
    rows.append(("FULL inversion", full_t * 1e3, full_t))

    print("| phase | per-step (ms) | x Nr total (s) | % of full |")
    print("|---|---|---|---|")
    for name, per_ms, tot in rows:
        print(f"| {name} | {per_ms:.2f} | {tot:.4f} | "
              f"{100 * tot / full_t:.0f}% |")
    gf = 2 * n**3 / full_t / 1e9
    print(f"\nFULL: {full_t*1e3:.1f} ms = {gf:.0f} GFLOP/s (2n^3 convention)")


if __name__ == "__main__":
    main()
