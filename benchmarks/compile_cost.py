"""Measure trace+compile seconds vs Nr for the in-place engines
(VERDICT r3 #6): the evidence behind ``MAX_UNROLL_NR`` — the unrolled
trace's compile cost grows with Nr (every super-step is cloned into the
graph), the fori_loop engines' does not.

Run on the 8-virtual-device CPU mesh (same environment as the test
suite); compile cost is a host/XLA property, so CPU numbers are the
right evidence for the dispatch threshold used on all backends.

Usage:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/compile_cost.py
"""

import os
import time

# This environment preloads jax at interpreter start (sitecustomize), so
# env mutation alone is too late — force the platform through jax.config
# before any backend initializes (same dance as tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()
assert jax.device_count() == 8, jax.device_count()


def compile_1d(n, m, unroll, **kw):
    from tpu_jordan.parallel import make_mesh
    from tpu_jordan.parallel.layout import CyclicLayout
    from tpu_jordan.parallel.ring_gemm import _to_identity_padded_blocks
    from tpu_jordan.parallel.sharded_inplace import (
        compile_sharded_jordan_inplace,
    )
    from tpu_jordan.ops import generate

    mesh = make_mesh(8)
    lay = CyclicLayout.create(n, m, 8)
    a = generate("absdiff", (n, n), jnp.float32)
    W = _to_identity_padded_blocks(a, lay, mesh)
    t0 = time.perf_counter()
    compile_sharded_jordan_inplace(W, mesh, lay, unroll=unroll, **kw)
    return lay.Nr, time.perf_counter() - t0


def compile_2d(n, m, unroll, **kw):
    from tpu_jordan.parallel import make_mesh_2d
    from tpu_jordan.parallel.layout import CyclicLayout2D
    from tpu_jordan.parallel.jordan2d import scatter_matrix_2d
    from tpu_jordan.parallel.jordan2d_inplace import (
        compile_sharded_jordan_inplace_2d,
    )
    from tpu_jordan.ops import generate

    mesh = make_mesh_2d(2, 4)
    lay = CyclicLayout2D.create(n, m, 2, 4)
    a = generate("absdiff", (n, n), jnp.float32)
    W = scatter_matrix_2d(a, lay, mesh)
    t0 = time.perf_counter()
    compile_sharded_jordan_inplace_2d(W, mesh, lay, unroll=unroll, **kw)
    return lay.Nr, time.perf_counter() - t0


def main():
    # Fixed m=16 so Nr sweeps via n without huge arrays; compile cost
    # depends on graph size (Nr), not on n's magnitude.  Round 5 adds
    # the grouped (k=2) and swap-free variants: the grouped-fori and
    # swap-free engines must stay flat in Nr (the bench capture ladder
    # and the pod-scale engines depend on it).
    m = 16
    print("| engine | Nr | unrolled s | fori s |")
    print("|---|---|---|---|")
    for Nr in (16, 32, 64, 128):
        n = Nr * m
        for label, kw in (("1D p=8", {}), ("1D p=8 k=2", {"group": 2}),
                          ("1D p=8 SF", {"swapfree": True})):
            row = [label, str(Nr)]
            for unroll in (True, False):
                if (unroll and Nr > 64) or (unroll and kw.get("swapfree")):
                    row.append("—")     # no unrolled swap-free flavor
                    continue
                _, secs = compile_1d(n, m, unroll, **kw)
                row.append(f"{secs:.1f}")
            print("| " + " | ".join(row) + " |")
    for Nr in (16, 32, 64, 128):
        n = Nr * m
        for label, kw in (("2D 2x4", {}), ("2D 2x4 k=2", {"group": 2}),
                          ("2D 2x4 SF", {"swapfree": True})):
            row = [label, str(Nr)]
            for unroll in (True, False):
                if (unroll and Nr > 64) or (unroll and kw.get("swapfree")):
                    row.append("—")
                    continue
                _, secs = compile_2d(n, m, unroll, **kw)
                row.append(f"{secs:.1f}")
            print("| " + " | ".join(row) + " |")


if __name__ == "__main__":
    main()
