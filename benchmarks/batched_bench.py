"""Batched-inversion benchmark (the north-star vmap capability,
BASELINE.md: "Batched 512x(2048x2048) Jordan solves").

Usage: python benchmarks/batched_bench.py [B,n,m ...]

Measures ``ops.batched.batched_jordan_invert`` on the real chip with the
slope-timing harness and prints one line per config with the 2n³·B flop
convention.  Results are recorded in benchmarks/PHASES.md.
"""

import sys
import time


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_jordan.ops import batched_jordan_invert, residual_inf_norm
    from tpu_jordan.utils.benchmarking import slope_time

    configs = [(512, 512, 64), (64, 1024, 128), (8, 2048, 128)]
    if len(sys.argv) > 1:
        configs = [tuple(map(int, c.split(","))) for c in sys.argv[1:]]

    rng = np.random.default_rng(0)
    for B, n, m in configs:
        # Well-scaled gaussian batch (the batched regime's natural
        # workload; |i−j| is a single fixed matrix, pointless batched).
        a = jnp.asarray(rng.standard_normal((B, n, n)), jnp.float32)
        t0 = time.perf_counter()
        inv, sing = batched_jordan_invert(a, block_size=m)
        jax.block_until_ready(inv)
        compile_s = time.perf_counter() - t0
        nsing = int(jnp.sum(sing))
        # Residual on one element (upcycled check, not the timed path).
        rel = float(residual_inf_norm(a[0], inv[0]))
        per = slope_time(
            lambda v: batched_jordan_invert(v, block_size=m)[0], (a,),
            r1=2, r2=6,
        )
        gf = 2.0 * n**3 * B / per / 1e9
        print(f"B={B} n={n} m={m}: {per*1e3:8.1f} ms  {gf:7.0f} GFLOP/s "
              f"(2n^3B)  residual[0]={rel:.1e}  singular={nsing}/{B} "
              f"(compile {compile_s:.0f}s)", flush=True)


if __name__ == "__main__":
    main()
