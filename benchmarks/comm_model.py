"""Analytic communication/compute model for the distributed in-place
engines (VERDICT r3 #5): predicts wall time and parallel efficiency for
the north-star configs on real TPU pods, and sanity-checks itself against
the measured CPU-mesh runs and the measured single-chip v5e phase model.

Per-super-step collective inventory (counted from the engines — reference
analogs main.cpp:1074 (custom pivot all-reduce), 1097 (pivot-row bcast),
1122-1129 (row-swap exchange)):

  1D (parallel/sharded_inplace.py::_step):
    * 3 scalar pmin/psum (pivot reduction)            — latency only
    * H psum:        (m, m)        over p
    * row_piv psum:  (m, N)        over p
    * row_t psum:    (m, N)        over p
  2D (parallel/jordan2d_inplace.py::_step2d, round-4 column-parallel
  probe):
    * 3 scalar pmin/psum over the whole mesh          — latency only
    * H psum:        (m, m)        over pr*pc
    * row_piv psum:  (m, N/pc)     along pr
    * row_t psum:    (m, N/pc)     along pr
    * chunk/E psum:  (N/pr, m)     along pc   (pre-swap broadcast; serves
                                               candidates AND multipliers)
    * swap fix-up:   (m, m)        along pc
    plus the 2D unscramble (after the loop): 2 x (N/pr, m) along pc per
    step.
  Swap-free (sharded_inplace.py::_step_swapfree, jordan2d_inplace.py::
  _step2d_swapfree):
    * the row_t psum, the 2D swap fix-up, and the per-step 2D
      unscramble are DELETED; in their place ONE bucketed-ppermute
      permutation per sharded axis after the loop
      (parallel/permute.py), charged by ``_bucketed_permute`` —
      axis−1 single-hop rounds of one padded shard-size bucket, valid
      under both gather modes (residency stays at one shard).

The one-hot psums are semantically broadcasts but lower as all-reduces;
ring all-reduce of S bytes over an axis of a chips with W bytes/s
per-direction links is modeled as T = S*(a-1)/a / W (reduce-scatter +
all-gather riding both directions).  Scalar collectives are charged
latency only.

Compute terms per step, calibrated on the measured v5e phase model
(benchmarks/PHASES.md "Post-fix phase model": 8192 m=256 = 35 ms
eliminate + 35 ms probe + ~8 ms glue = 78.7 ms):
  * eliminate: 2*(N/P_row)*m*N flops at the chip's measured fp32 matmul
    envelope (v5e: 30.7 TF/s), floored by the shard's HBM read-modify-
    write;
  * probe: c_probe * live_candidates * m^3 elementwise-pass cost —
    c_probe calibrated to the same 35 ms (1D probes (Nr-t)/p candidates
    per worker; 2D probes (Nr-t)/(pr*pc) since the round-4
    column-parallel probe splits candidates across mesh columns);
  * glue (swaps, normalize, row writes): 0.5 HBM shard passes.

Chip constants: measured for v5e; v4/v5p matmul envelopes scaled from
the public bf16 peaks by the v5e-measured fp32-HIGHEST/bf16 ratio
(30.7/197 ~ 1/6.4), ICI per-link one-directional bandwidths and HBM
bandwidths from public TPU specs (How to Scale Your Model).  Predictions,
not measurements — the point is WHERE the collectives start to dominate,
not 3-digit accuracy.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Chip:
    name: str
    mxu_f32: float      # fp32-HIGHEST matmul envelope, FLOP/s
    hbm: float          # bytes/s
    ici: float          # per-link one-directional bytes/s
    vpu_scale: float    # probe-rate multiplier vs the v5e calibration


# v5e measured; v4/v5p scaled (bf16 peaks 197/275/459 TF/s; HBM
# 0.81/1.23/2.77 TB/s; ICI links 4.5e10/4.5e10/9e10 B/s).  vpu_scale
# tracks the clock/lane ratio (~bf16 ratio is MXU-count-driven, the VPU
# grows less) — held conservative at the HBM ratio.
V5E = Chip("v5e", 30.7e12, 0.81e12, 4.5e10, 1.0)
V4 = Chip("v4", 43e12, 1.23e12, 4.5e10, 1.5)
V5P = Chip("v5p", 72e12, 2.77e12, 9.0e10, 3.4)

LATENCY = 2e-6          # per collective, seconds (ICI hop + launch)
C_PROBE_V5E = 4.07e-12  # s per candidate-element pass (35 ms @ 8192/256)

# The projected north-star configurations — ONE place (ISSUE 2
# satellite: these rows were previously duplicated between this module's
# ``main`` and the PHASES.md projection tables; the tuner's cost hook is
# a third consumer).  Each row: (n, m, pr, pc, chip_name, group,
# swapfree).  ``main`` renders them; ``topology_params`` exposes them.
NORTH_STAR_ROWS = (
    # v4-8 (4 chips) and v5e-8 class, 8192 (plain vs grouped vs SF).
    (8192, 256, 8, 1, "v5e", 1, False),
    (8192, 256, 8, 1, "v5e", 4, False),
    (8192, 256, 8, 1, "v5e", 1, True),
    (8192, 256, 2, 4, "v5e", 1, False),
    (8192, 256, 2, 4, "v5e", 4, False),
    (8192, 512, 4, 1, "v4", 1, False),
    (8192, 512, 2, 2, "v4", 1, False),
    # v5p-32, 32768 (the 2D north star; 1D shown for contrast).
    (32768, 512, 32, 1, "v5p", 1, False),
    (32768, 512, 32, 1, "v5p", 4, False),
    (32768, 512, 32, 1, "v5p", 1, True),
    (32768, 512, 4, 8, "v5p", 1, False),
    (32768, 512, 4, 8, "v5p", 4, False),
    (32768, 256, 4, 8, "v5p", 4, False),
    (32768, 512, 4, 8, "v5p", 1, True),
    # v5p-64, 65536.
    (65536, 512, 64, 1, "v5p", 1, False),
    (65536, 512, 64, 1, "v5p", 1, True),
    (65536, 512, 8, 8, "v5p", 1, False),
    (65536, 512, 8, 8, "v5p", 1, True),
    (65536, 512, 8, 8, "v5p", 4, False),
    (65536, 256, 8, 8, "v5p", 4, False),
)


def topology_params() -> dict:
    """The public, single source of the chip/topology constants.

    Consumed by (a) this module's own ``main`` (the PHASES.md projection
    tables are regenerated from its output) and (b) the autotuner's cost
    hook (``tpu_jordan/tuning/registry.py``), so the v5p/v5e/v4 envelope,
    HBM, and ICI numbers can never drift between the projections and the
    product's engine ranking.

    Returns::

        {"chips":        {name: Chip},      # measured/scaled constants
         "backend_chip": {backend: name},   # cost-ranking stand-in per
                                            # jax backend ("cpu"/"axon"
                                            # rank with the calibrated
                                            # v5e model: the tuner needs
                                            # RELATIVE engine costs, not
                                            # wall-clock truth)
         "latency":      seconds per collective,
         "c_probe_v5e":  probe calibration constant,
         "north_star":   NORTH_STAR_ROWS}
    """
    return {
        "chips": {c.name: c for c in (V5E, V4, V5P)},
        "backend_chip": {"tpu": "v5e", "cpu": "v5e", "axon": "v5e"},
        "latency": LATENCY,
        "c_probe_v5e": C_PROBE_V5E,
        "north_star": NORTH_STAR_ROWS,
    }


def _allreduce(S: float, a: int, chip: Chip) -> float:
    return 0.0 if a == 1 else S * (a - 1) / a / chip.ici + LATENCY


def _bucketed_permute(S: float, a: int, chip: Chip) -> float:
    """The swap-free engines' deferred permutation along one mesh axis
    (parallel/permute.py): a−1 single-hop ``ppermute`` rounds of one
    padded shard-size bucket S (static shapes force worst-case padding,
    so every round ships a full shard).  The forward and backward
    rotation buffers ride OPPOSITE ring directions concurrently, so
    wall time is the floor(a/2) forward rounds — the reason the
    implementation rotates one hop per round instead of direct
    shift-by-d ppermutes, whose min(d, a−d) link hops would sum to
    ~a²/4 shard-times."""
    return 0.0 if a == 1 else (a // 2) * (S / chip.ici + LATENCY)


def predict(n: int, m: int, pr: int, pc: int, chip: Chip,
            measured_single: float | None = None, group: int = 1,
            swapfree: bool = False):
    """Returns dict of phase seconds + efficiency for an (pr, pc) mesh
    (pc=1 -> the 1D row-cyclic engine).

    ``group=k > 1`` models the delayed-group-update engines
    (parallel/sharded_inplace.py::_gstep, jordan2d_inplace.py::_gstep2d):
      * the trailing shard rewrite happens ONCE per group (HBM
        read-modify-write divided by k; matmul flops unchanged but the
        contraction dim is k·m — modeled at the same envelope,
        conservative: the measured single-chip win at 16384 came
        precisely from this term);
      * eager side updates add 2·rows·(j·m)·m flops for the probed
        column and 2·m·(j·m)·(N/pc) for the pivot row at inner position
        j (avg j = (k−1)/2) — the few-% tax the single-chip engine pays;
      * the two (m, N/pc) row psums + the (m, m) swap fix-up fuse into
        ONE stacked (2m, N/pc + k·m + m) psum along "pr": same bytes to
        first order, ~half the per-step collective LATENCY rounds — the
        term that dominates the v5p projections.
    """
    if swapfree and group > 1:
        # Mirrors the product contract (driver.resolve_engine): no
        # grouped swap-free engine exists.
        raise ValueError("swapfree has no grouped variant")
    Nr = -(-n // m)
    N = Nr * m
    P = pr * pc
    k = max(1, min(group, Nr))
    c_probe = C_PROBE_V5E / chip.vpu_scale

    elim = probe = comm = glue = 0.0
    for t in range(Nr):
        j = t % k                                # position within group
        fl = 2.0 * (N / pr) * m * (N / pc)
        rmw = 2.0 * (N / pr) * (N / pc) * 4
        if k == 1:
            elim += max(fl / chip.mxu_f32, rmw / chip.hbm)
            glue += 0.5 * rmw / chip.hbm
        else:
            # Trailing update amortized over the group; eager side
            # updates (column + pivot row) charged per step.
            elim += max(fl / chip.mxu_f32, rmw / k / chip.hbm)
            eager = (2.0 * (N / pr) * (j * m) * m
                     + 2.0 * m * (j * m) * (N / pc))
            elim += eager / chip.mxu_f32
            # Row/chunk-granular per-step writes instead of a shard pass.
            glue += (0.5 * rmw / k + 3 * 4 * m * (N / pc)) / chip.hbm
        # probe: live candidates on the probing workers.  The round-4
        # column-parallel probe broadcasts the t-chunk panel along "pc"
        # (the SAME panel the eliminate needed anyway — bytes unchanged)
        # and splits candidates across mesh columns, so 2D probe work
        # divides by pr*pc, not pr.
        live = max(1, (Nr - t) // P)
        probe += c_probe * live * m**3
        # collectives.
        comm += 3 * LATENCY                      # scalar pivot reduction
        comm += _allreduce(4 * m * m, P, chip)   # H
        if swapfree:
            # The implicit-permutation engine: ONE pivot-row psum; the
            # row_t broadcast does not exist (no swap).  The deferred
            # price is the one-shot permutation below.
            comm += _allreduce(4 * m * (N / pc), pr, chip)
        elif k == 1:
            comm += 2 * _allreduce(4 * m * (N / pc), pr, chip)  # both rows
        else:
            # ONE stacked psum: both rows + their U rows + the t-block.
            comm += _allreduce(
                4 * 2 * m * ((N / pc) + k * m + m), pr, chip)
        if pc > 1:
            comm += _allreduce(4 * (N / pr) * m, pc, chip)  # chunk/E panel
            if k == 1 and not swapfree:
                comm += _allreduce(4 * m * m, pc, chip)  # swap fix-up
            if not swapfree:
                # Per-step psum unscramble — the swap-free 2D engine
                # deletes it (rows+columns repaired in the gather fold).
                comm += 2 * _allreduce(4 * (N / pr) * m, pc, chip)
    if swapfree:
        # The deferred permutations, charged as MEASURED terms of the
        # bucketed-ppermute implementation (parallel/permute.py): rows
        # move only along the row axis, column chunks (2D) only along
        # the column axis, each in axis−1 single-hop rounds of one
        # padded shard-size bucket — residency stays at one shard, so
        # this term applies to gather=False too (the old accounting
        # charged zero under a gather=True-only contract and called
        # sharded output "comm-neutral" via a hypothetical all-gather
        # reshuffle; both are gone).  The full-window probe loses the
        # shrinking window: +~2x probe launches, charged.
        S_shard = 4.0 * (N / pr) * (N / pc)
        comm += _bucketed_permute(S_shard, pr, chip)      # rows
        if pc > 1:
            comm += _bucketed_permute(S_shard, pc, chip)  # column chunks
        probe *= 2.0
    total = elim + probe + comm + glue
    out = {"elim": elim, "probe": probe, "comm": comm, "glue": glue,
           "total": total}
    if P == 1:
        out["efficiency"] = 1.0
    else:
        single = (measured_single if measured_single is not None
                  else predict(n, m, 1, 1, chip)["total"])
        out["efficiency"] = single / (P * total)
    return out


def _fmt(n, m, pr, pc, chip, group=1, swapfree=False):
    r = predict(n, m, pr, pc, chip, group=group, swapfree=swapfree)
    mesh = f"{pr}x{pc}" if pc > 1 else f"1D p={pr}"
    if group > 1:
        mesh += f" k={group}"
    if swapfree:
        mesh += " SF"
    gf = 2.0 * n**3 / r["total"] / 1e9
    return (f"| {chip.name} {mesh} | {n} | {m} | {r['elim']*1e3:8.1f} | "
            f"{r['probe']*1e3:8.1f} | {r['comm']*1e3:8.1f} | "
            f"{r['total']*1e3:8.1f} | {gf:10,.0f} | "
            f"{r['efficiency']*100:5.0f}% |")


def projection_rows() -> list:
    """The north-star projections as structured rows (ISSUE 14
    satellite): every future calibration round re-emits THIS one
    artifact from ``topology_params()`` (``--comm-project``) and diffs
    it, instead of hand-running the table and eyeballing — the
    4×8@32768 79.9 ms / 8×8@65536 244.7 ms numbers quoted around the
    repo are rows of this list, regenerable on demand."""
    chips = topology_params()["chips"]
    rows = []
    for n, m, pr, pc, chip_name, g, sf in NORTH_STAR_ROWS:
        r = predict(n, m, pr, pc, chips[chip_name], group=g, swapfree=sf)
        rows.append({
            "n": n, "m": m, "pr": pr, "pc": pc, "chip": chip_name,
            "group": g, "swapfree": sf,
            "elim_ms": round(r["elim"] * 1e3, 1),
            "probe_ms": round(r["probe"] * 1e3, 1),
            "comm_ms": round(r["comm"] * 1e3, 1),
            "glue_ms": round(r["glue"] * 1e3, 1),
            "total_ms": round(r["total"] * 1e3, 1),
            "gflops": round(2.0 * n**3 / r["total"] / 1e9, 1),
            "efficiency": round(r["efficiency"], 4),
        })
    return rows


def main(argv=None):
    import json
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if "--comm-project" in argv:
        # ONE diffable JSON artifact re-emitted from topology_params()
        # — the calibration-round workflow (ISSUE 14 satellite).
        params = topology_params()
        print(json.dumps({
            "metric": "comm_projection",
            "chips": {name: {"mxu_f32": c.mxu_f32, "hbm": c.hbm,
                             "ici": c.ici, "vpu_scale": c.vpu_scale}
                      for name, c in params["chips"].items()},
            "latency_s": params["latency"],
            "c_probe_v5e": params["c_probe_v5e"],
            "rows": projection_rows(),
        }))
        return
    print("Sanity: single-chip v5e model vs measured 78.7 ms @ 8192 m=256")
    r = predict(8192, 256, 1, 1, V5E)
    print({k: round(v * 1e3, 1) for k, v in r.items() if k != "efficiency"})
    print("Grouped sanity: v5e single-chip 16384 m=128 k=2 "
          "(measured 396 ms)")
    r = predict(16384, 128, 1, 1, V5E, group=2)
    print({k: round(v * 1e3, 1) for k, v in r.items() if k != "efficiency"})
    print()
    print("| mesh | n | m | elim ms | probe ms | comm ms | total ms "
          "| GFLOP/s | par.eff |")
    print("|---|---|---|---|---|---|---|---|---|")
    chips = topology_params()["chips"]
    for n, m, pr, pc, chip_name, g, sf in NORTH_STAR_ROWS:
        print(_fmt(n, m, pr, pc, chips[chip_name], g, sf))


if __name__ == "__main__":
    main()
