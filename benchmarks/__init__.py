"""Benchmarks as an importable package: ``benchmarks.comm_model`` is the
single source of the chip/topology constants (``topology_params``), and
the autotuner's cost hook (``tpu_jordan/tuning/registry.py``) imports it
from here when the repo root is on ``sys.path`` (the tuner falls back to
a file-path import otherwise, so an installed ``tpu_jordan`` keeps
working without this directory)."""
