# tpu_jordan build/run entry points.
#
# Replaces the reference's Makefile (Makefile:1-6: mpicxx -Ofast + clean)
# with the TPU-native equivalents: a `tpu` run target (the analog of
# `mpirun -np P ./a.out n m [file]`), the native helper library, tests,
# and the benchmark.

CXX      ?= g++
CXXFLAGS ?= -O3 -fPIC -Wall
N        ?= 4096
M        ?= 128
WORKERS  ?= 1
REQUESTS  ?= 64
BATCH_CAP ?= 8

.PHONY: all native tpu test smoke serve-demo solve-demo chaos-demo fleet-demo autoscale-demo update-demo capacity-demo comm-demo work-demo lp-demo ckpt-demo metrics-demo slo-demo blackbox numerics-demo bench bench-dip bench-check clean

REPLICAS ?= 3

all: native

# Native C ABI helpers (fast matrix-file parser; loaded via ctypes).
native: tpu_jordan/_native.so

tpu_jordan/_native.so: native/matrix_io.cpp
	$(CXX) $(CXXFLAGS) -shared -o $@ $<

# Run the solver on the TPU (the reference's `mpirun -np P ./a.out n m`).
# The native build is best-effort: io.py has a transparent Python fallback.
tpu:
	-$(MAKE) native
	python -m tpu_jordan $(N) $(M) --workers $(WORKERS)

test:
	python -m pytest tests/ -q

# Fast signal tier (< 2 min): one engine-parity case per family + layout
# + entry + a serve round-trip.  Full coverage stays in `make test`.
smoke:
	python -m pytest tests/ -q -m smoke

# The dynamic-batching inversion service demo (docs/SERVING.md): mixed
# request sizes micro-batched through the bucketed AOT executable
# cache; prints one JSON line of per-bucket stats.
serve-demo:
	python -m tpu_jordan $(N) $(M) --serve-demo \
	  --serve-requests $(REQUESTS) --batch-cap $(BATCH_CAP)

# The solve workloads (ISSUE 11, docs/WORKLOADS.md): X = A^-1 B by
# Gauss-Jordan on [A | B] (no inverse ever formed), the pivot-free
# --assume spd fast path on the KMS SPD fixture, complex64, and lstsq
# via the normal equations — all through the workload-scoped
# engine-auto ladder, with the CLI's 0/1/2 exit taxonomy.
solve-demo:
	python -m tpu_jordan 256 64 --workload solve --rhs 4 \
	  --generator rand --quiet
	python -m tpu_jordan 192 64 --workload solve --rhs 2 --assume spd \
	  --generator kms --quiet
	python -m tpu_jordan 128 32 --workload solve --rhs 2 \
	  --dtype complex64 --generator crand --quiet
	python -m tpu_jordan 128 32 --workload lstsq --rhs 2 \
	  --generator rand --quiet

# Chaos demo + validation (docs/RESILIENCE.md): the same deterministic
# request stream served fault-free and under a seeded FaultPlan
# (compile failures, transient execute errors, NaN result corruption,
# plan-cache write failures); the checker proves every injected fault
# was retried, degraded, or typed — none silent — and every response
# bit-matched the fault-free replay or carried a typed error.
chaos-demo:
	python -m tpu_jordan 96 32 --chaos-demo \
	  --serve-requests $(REQUESTS) --batch-cap 4 --quiet \
	  > /tmp/tpu_jordan_chaos.json
	python tools/check_chaos.py /tmp/tpu_jordan_chaos.json

# Fleet demo + validation (docs/FLEET.md): single-replica vs N-replica
# throughput on the same deterministic stream, then the SAME stream
# under seeded replica_kill chaos — the supervisor warm-replaces each
# victim with zero compiles (shared executor store) and zero
# measurements (read-only pre-tuned plan cache), the router re-queues
# the victim's queued work, and the checker proves every response
# bit-matched the fault-free replay or carried a typed error (exit 2 =
# silent loss).  On parallel hardware pass a demanding scaling floor:
# make fleet-demo FLEET_ARGS="--scaling-floor 2.5".
fleet-demo:
	python -m tpu_jordan 96 32 --fleet-demo --replicas $(REPLICAS) \
	  --serve-requests 60 --batch-cap 4 --quiet $(FLEET_ARGS) \
	  > /tmp/tpu_jordan_fleet.json
	python tools/check_fleet.py /tmp/tpu_jordan_fleet.json

# Autoscaler demo + validation (ISSUE 18, docs/FLEET.md): one seeded
# burst->idle->recovery trace through a floor-sized fleet under the
# SLO-driven FleetAutoscaler — sustained deadline burn pages the
# burn-rate monitor, which scales the pool toward the ceiling and
# pre-sheds new submissions typed at the router; the idle phase drains
# parked slots back to the floor; the recovery wave serves clean.  The
# checker re-derives EVERY scale/drain/pre-shed decision from the burn
# evidence recorded alongside it (exit 2 = a silent p99 breach or an
# unexplained scale action).
autoscale-demo:
	python -m tpu_jordan 48 16 --autoscale-demo --replicas $(REPLICAS) \
	  --serve-requests 32 --batch-cap 4 --quiet \
	  > /tmp/tpu_jordan_autoscale.json
	python tools/check_autoscale.py /tmp/tpu_jordan_autoscale.json

# Resident-update demo + validation (ISSUE 12, docs/WORKLOADS.md):
# a resident handle streams rank-32 Sherman-Morrison-Woodbury updates
# through the O(n^2 k) update lane at the acceptance scale (2048^2,
# k=32 <= n/8) — the ledger accounts every update as
# refreshed|re_inverted|gated, warm update latency must beat warm
# re-invert, the update executable's cost_analysis FLOPs must sit
# below the fresh-invert executable's, and a seeded replica_kill
# mid-update-stream must leave a bit-matched, gate-verified resident
# inverse (exit 2 = a silently stale inverse).  This row is the
# demo gate for the update workload, like chaos-demo/fleet-demo for
# theirs.
update-demo:
	python -m tpu_jordan 2048 128 --update-demo --rank 32 --updates 6 \
	  --replicas $(REPLICAS) --kills 1 --quiet \
	  > /tmp/tpu_jordan_update.json
	python tools/check_update.py /tmp/tpu_jordan_update.json

# Capacity demo + validation (ISSUE 13, docs/OBSERVABILITY.md): a
# warmed service under a resident-handle byte budget — lane bytes
# projected before any compile, LRU budget eviction with journey-hop +
# flight-recorder evidence, the typed CapacityExceededError at submit
# when everything evictable is pinned, and the ledger reconciliation
# bytes_created == bytes_live + bytes_evicted per class (exit 2 =
# unmetered residency / a silent eviction).  This row is the capacity
# observatory's demo gate, like update-demo/fleet-demo for theirs.
capacity-demo:
	python -m tpu_jordan 96 32 --capacity-demo --quiet \
	  > /tmp/tpu_jordan_capacity.json
	python tools/check_capacity.py /tmp/tpu_jordan_capacity.json

# Comm demo + validation (ISSUE 14 + the ISSUE 15 solve legs + the
# ISSUE 16 probe-ahead legs, docs/OBSERVABILITY.md): nine tiny
# distributed solves (1D + 2D meshes, both gather modes, a grouped
# engine, a ragged problem size, the two distributed-SOLVE legs — the
# [A | B] elimination's own inventory — and the lookahead invert +
# solve legs, whose reordered schedule must keep the collective
# multiset identical) each reconciling the collective
# multiset the traced program actually issued against the
# layout-derived analytical inventory, plus one deliberate
# measured-vs-projected drift leg whose out-of-band ratio must be a
# RECORDED comm_drift event (exit 2 = an unaccounted collective or a
# silent drift).  This row is the communication observatory's demo
# gate, like capacity-demo/update-demo/fleet-demo for theirs.
comm-demo:
	python -m tpu_jordan 48 8 --comm-demo --quiet \
	  > /tmp/tpu_jordan_comm.json
	python tools/check_comm.py /tmp/tpu_jordan_comm.json

# Work-observatory demo + validation (ISSUE 19,
# docs/OBSERVABILITY.md): six tiny distributed solves (1D + 2D meshes,
# invert + solve workloads, a ragged size whose padded tail skews the
# shares and an aligned size whose penalty must pin to exactly 0) —
# each leg's per-worker analytical FLOP shares summing EXACTLY to the
# engine's convention total, re-derived by the checker from the layout
# math alone, and each executable judged against cost_analysis — plus
# the fleet-skew legs: a synthetic straggler that MUST be a recorded
# straggler_suspected event, a layout-attributed spread that must stay
# clean, and the recovery transition (exit 2 = unaccounted work or an
# unsupported straggler verdict).  This row is the work observatory's
# demo gate, like comm-demo for the communication observatory.
work-demo:
	python -m tpu_jordan 48 8 --work-demo --quiet \
	  > /tmp/tpu_jordan_work.json
	python tools/check_work.py /tmp/tpu_jordan_work.json

# LP/QP driver demo + validation (ISSUE 17, docs/WORKLOADS.md): four
# seeded optimization runs (LP well/ill revised simplex, QP well/ill
# primal active-set) stream correlated invert(resident=True) + rank-k
# update + verification-solve traffic through a warmed replica fleet —
# convergence judged by the solver's own eps*n*kappa gate and
# RE-DERIVED by the checker from the report's iterate residuals — plus
# the zero-drift-budget re_invert probe, a seeded replica_kill run that
# must bit-match its fault-free replay, and the batched update-lane
# amortization measurement (occupancy > 1 must beat one-per-launch;
# exit 2 = silent divergence).  This row is the demo gate for the
# optimization-driver workload, like update-demo/fleet-demo for theirs.
lp-demo:
	python -m tpu_jordan 16 8 --lp-demo --dtype float64 \
	  --replicas $(REPLICAS) --kills 1 --batch-cap 4 --quiet \
	  > /tmp/tpu_jordan_lp.json
	python tools/check_lp.py /tmp/tpu_jordan_lp.json

# Checkpoint/resume demo + validation (ISSUE 20, docs/RESILIENCE.md):
# four preempt-and-resume legs (single-device invert, 1D distributed
# solve, a resumable LP stream, and a fleet replica killed mid-sweep)
# each recover from the last durable superstep checkpoint and must
# bit-match the uninterrupted baseline with zero segment compiles on
# the warm resume.  check_ckpt exit 2 is the silent-loss alarm: a
# divergent resume, a durable checkpoint silently ignored, or a
# checkpoint ledger that does not add up.
ckpt-demo:
	python -m tpu_jordan 96 16 --ckpt-demo --quiet \
	  > /tmp/tpu_jordan_ckpt.json
	python tools/check_ckpt.py /tmp/tpu_jordan_ckpt.json

# SLO demo + validation (docs/OBSERVABILITY.md): the fleet demo with
# the --slo-report leg — declarative per-bucket availability SLOs
# evaluated by multi-window burn rate over registry snapshots
# bracketing the fleet phases; check_slo re-derives every burn rate
# and page decision from the report's own counts (exit 2 = the fleet
# is actually burning budget past its thresholds).
slo-demo:
	python -m tpu_jordan 96 32 --fleet-demo --replicas $(REPLICAS) \
	  --serve-requests 60 --batch-cap 4 --quiet --slo-report \
	  $(FLEET_ARGS) > /tmp/tpu_jordan_slo.json
	python tools/check_slo.py /tmp/tpu_jordan_slo.json
	python tools/check_fleet.py /tmp/tpu_jordan_slo.json

# Flight-recorder demo + validation (docs/OBSERVABILITY.md): the chaos
# demo with the always-on black box dumped via --blackbox-out; the
# checker reconstructs every request's journey from the raw dump alone
# and walks each injected fault to its recorded consequence.
blackbox:
	python -m tpu_jordan 96 32 --chaos-demo --serve-requests $(REQUESTS) \
	  --batch-cap 4 --quiet --blackbox-out /tmp/tpu_jordan_blackbox.json \
	  > /dev/null
	python tools/check_blackbox.py /tmp/tpu_jordan_blackbox.json

# Telemetry demo + validation (docs/OBSERVABILITY.md): a small solve
# and a serve burst, each exporting the process-wide tpu_jordan_*
# metrics (Prometheus text) and the solve's span tree (Chrome trace
# JSON, viewable in Perfetto); the checker validates both formats and
# the metric namespace.
metrics-demo:
	python -m tpu_jordan 256 64 --quiet \
	  --metrics-out /tmp/tpu_jordan_solve.prom \
	  --trace-json /tmp/tpu_jordan_solve_trace.json
	python -m tpu_jordan 256 64 --serve-demo --serve-requests 24 --quiet \
	  --metrics-out /tmp/tpu_jordan_serve.prom
	python tools/check_telemetry.py /tmp/tpu_jordan_solve.prom \
	  /tmp/tpu_jordan_serve.prom /tmp/tpu_jordan_solve_trace.json

# Numerics-observatory demo + validation (docs/OBSERVABILITY.md,
# ISSUE 10): one seeded ill-conditioned bf16 solve with the full
# per-superstep numerics trace — the residual gate fails, refine
# diverges, the fp32 re-solve recovers — and the checker proves every
# degradation rung is causally preceded by a numerics_spike event in
# the flight recorder (exit 2 = an unexplained rung).
numerics-demo:
	python -m tpu_jordan 16 8 --numerics-demo --quiet \
	  > /tmp/tpu_jordan_numerics.json
	python tools/check_numerics.py /tmp/tpu_jordan_numerics.json
	python -m tpu_jordan 16 8 --numerics-demo --workload solve --quiet \
	  > /tmp/tpu_jordan_numerics_solve.json
	python tools/check_numerics.py /tmp/tpu_jordan_numerics_solve.json

bench: native
	python bench.py

# The 4096² dip guard row alone (ISSUE 6 satellite; BASELINE.md "The
# r04→r05 4096² dip"): plain + fused-Pallas 4096² captures with
# median-of-3 spread, compared against the BENCH_r04 11.8 TF/s
# reference — `regressed` flips only when the shortfall exceeds 10%
# AND the session's own spread cannot explain it.
bench-dip: native
	python bench.py --dip-guard

# The BENCH trajectory regression sentinel (ISSUE 10; docs/
# OBSERVABILITY.md): compares the newest round's steady-state rows —
# never first-call compile-inclusive times — against the best prior
# round, flagging only shortfalls the rows' own spread/variance_flag
# cannot explain (exit 2 = unexplained regression; rows without
# robust-capture stats are unknown, not regressed).
bench-check:
	python tools/check_bench.py BENCH_r*.json

clean:
	rm -f tpu_jordan/_native.so
